"""Node-axis sharding: the edge-cut partitioner + halo-exchange rollout.

The contract (ISSUE 11 / ROADMAP item 1): the partitioned programs are
**bit-exact** to the unsharded packed rollout across P ∈ {1, 2, 4, 8} and
across a mid-run preempt/resume (same snapshot format, journal-verified);
the BFS-grow + refinement partitioner measurably buys locality (cut ≤
random-chop cut / 2 on the d=3 RRG); and the halo exchange moves only
boundary words (priced by ``halo_bytes_per_step`` and pinned structurally
by the graftcheck ``halo_rollout`` ledger row + graftlint GD013).
"""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from graphdyn.config import DynamicsConfig, SAConfig
from graphdyn.graphs import (
    edge_cut,
    erdos_renyi_graph,
    partition_ghosts,
    partition_graph,
    random_regular_graph,
)
from graphdyn.ops.packed import pack_spins, packed_rollout
from graphdyn.parallel.halo import (
    HaloProgram,
    build_halo_tables,
    gather_state,
    sa_halo_cols,
    sa_halo_uncols,
    scatter_state,
)
from graphdyn.parallel.mesh import device_pool, make_mesh


def _mesh(rep, node):
    return make_mesh(
        (rep, node), ("replica", "node"), devices=device_pool(rep * node)
    )


def _random_chop_cut(g, P, seed):
    """Edge cut of a random permutation chopped into P contiguous balanced
    parts — the no-locality baseline the partitioner must halve."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(g.n)
    part = np.empty(g.n, np.int32)
    base, rem = divmod(g.n, P)
    sizes = np.full(P, base)
    sizes[:rem] += 1
    bounds = np.concatenate([[0], np.cumsum(sizes)])
    for p in range(P):
        part[perm[bounds[p]:bounds[p + 1]]] = p
    return edge_cut(g, part)


# ---------------------------------------------------------------------------
# partitioner
# ---------------------------------------------------------------------------


def test_partition_layout_consistent():
    """order is a permutation; parts are balanced within the slack; the
    interior/boundary split is correct (interior rows have no cut edge,
    boundary rows have at least one)."""
    g = erdos_renyi_graph(300, 5.0 / 299, seed=2)
    for P in (1, 2, 4, 8):
        part = partition_graph(g, P, seed=0)
        assert part.P == P
        assert np.array_equal(np.sort(part.order), np.arange(g.n))
        assert part.counts.sum() == g.n
        if P > 1:
            assert part.counts.max() <= int(np.ceil(1.1 * (g.n / P + 1)))
        for p in range(P):
            seg = part.order[part.offsets[p]:part.offsets[p + 1]]
            assert (part.part[seg] == p).all()
            n_int = int(part.interior[p])
            for k, node in enumerate(seg):
                real = g.nbr[node][g.nbr[node] != g.n]
                crosses = (part.part[real] != p).any() if real.size else False
                assert crosses == (k >= n_int), (P, p, k)


def test_partition_p1_trivial_and_errors():
    g = random_regular_graph(64, 3, seed=0)
    part = partition_graph(g, 1)
    assert part.edge_cut == 0 and part.boundary.sum() == 0
    assert partition_ghosts(g, part)[0].size == 0
    with pytest.raises(ValueError, match="n_parts"):
        partition_graph(g, 0)
    with pytest.raises(ValueError, match="n_parts"):
        partition_graph(g, 65)


def test_partition_seed_deterministic():
    g = random_regular_graph(512, 3, seed=4)
    a = partition_graph(g, 4, seed=7)
    b = partition_graph(g, 4, seed=7)
    assert np.array_equal(a.part, b.part)
    assert np.array_equal(a.order, b.order)
    assert a.edge_cut == b.edge_cut


def test_partition_quality_rrg_4096():
    """The regression the BFS-grow + refinement passes must keep buying:
    on the d=3 RRG at n=4096 the partitioner's edge cut is at most HALF a
    random contiguous chop's, at every shard count (measured ~0.41–0.45×
    at seed time — the bar has real margin, and a partitioner that decays
    to random assignment fails it immediately)."""
    g = random_regular_graph(4096, 3, seed=0)
    for P in (2, 4, 8):
        cut = partition_graph(g, P, seed=0).edge_cut
        baseline = _random_chop_cut(g, P, seed=1)
        assert cut <= baseline / 2, (P, cut, baseline)


# ---------------------------------------------------------------------------
# packed halo rollout: bit-exactness + layout plumbing
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("gname", ["rrg", "er"])
@pytest.mark.parametrize("rule,tie", [("majority", "stay"),
                                      ("minority", "change")])
def test_halo_rollout_bit_exact_all_shard_counts(gname, rule, tie):
    """packed_rollout(partition=) equals the unsharded program bitwise at
    P ∈ {1, 2, 4, 8}, on the regular AND ragged (ER, with ghost-padded
    neighbor slots) graphs, under both rule/tie families — the per-node
    arithmetic is the same carry-save/comparator program, so any
    divergence is a layout/exchange bug, not roundoff."""
    g = (random_regular_graph(258, 3, seed=2) if gname == "rrg"
         else erdos_renyi_graph(200, 4.0 / 199, seed=3))
    rng = np.random.default_rng(0)
    s = (2 * rng.integers(0, 2, size=(64, g.n)) - 1).astype(np.int8)
    sp = pack_spins(s)
    nbr, deg = jnp.asarray(g.nbr), jnp.asarray(g.deg)
    ref = np.asarray(packed_rollout(nbr, deg, jnp.asarray(sp), 30, rule, tie))
    for P in (1, 2, 4, 8):
        part = partition_graph(g, P, seed=0)
        got = np.asarray(packed_rollout(
            nbr, deg, jnp.asarray(sp), 30, rule, tie, partition=part
        ))
        np.testing.assert_array_equal(got, ref, err_msg=f"P={P}")


def test_halo_scatter_gather_roundtrip_and_bytes():
    g = random_regular_graph(130, 3, seed=1)
    part = partition_graph(g, 4, seed=0)
    tables = build_halo_tables(g, part)
    sp = np.asarray(pack_spins(
        (2 * np.random.default_rng(0).integers(0, 2, size=(32, g.n)) - 1)
        .astype(np.int8)
    ))
    assert np.array_equal(gather_state(tables, scatter_state(tables, sp)), sp)
    # useful words = Σ ghosts (mirrors the partitioner's ghost tables);
    # shipped words = the padded uniform slabs (>= useful, the honest wire
    # bill the gauge/bench report)
    ghosts = partition_ghosts(g, part)
    assert tables.n_halo_words == sum(x.size for x in ghosts)
    assert tables.n_slab_words == tables.P * sum(
        s.shape[1] for (_, s, _) in tables.schedule
    )
    assert tables.n_slab_words >= tables.n_halo_words > 0
    assert tables.halo_bytes_per_step(sp.shape[1]) == \
        4 * sp.shape[1] * tables.n_slab_words


def test_halo_program_emits_traffic_gauge(tmp_path):
    """While recording, every HaloProgram.advance emits the
    ``parallel.halo.bytes_per_step`` gauge with the byte model's value."""
    from graphdyn import obs
    from graphdyn.obs.recorder import read_ledger

    g = random_regular_graph(96, 3, seed=5)
    part = partition_graph(g, 2, seed=0)
    prog = HaloProgram(g, part, steps=3)
    sp = np.zeros((g.n, 2), np.uint32)
    path = str(tmp_path / "ledger.jsonl")
    with obs.recording(path):
        prog.fetch(prog.advance(prog.place(sp)))
    events, torn = read_ledger(path)
    assert torn == 0
    gauges = [e for e in events if e.get("ev") == "gauge"
              and e.get("name") == "parallel.halo.bytes_per_step"]
    assert gauges, events
    assert gauges[0]["value"] == prog.tables.halo_bytes_per_step(2)
    assert gauges[0]["attrs"]["P"] == 2


def test_sa_halo_cols_roundtrip():
    g = erdos_renyi_graph(77, 4.0 / 76, seed=9)
    part = partition_graph(g, 4, seed=0)
    tables = build_halo_tables(g, part)
    s = (2 * np.random.default_rng(3).integers(0, 2, size=(5, g.n)) - 1) \
        .astype(np.int8)
    cols = sa_halo_cols(tables, s)
    assert np.array_equal(sa_halo_uncols(tables, cols), s)
    # the zero column must read as spin 0 (ghost-padded neighbor slots)
    view = cols.reshape(5, tables.P, tables.n_rows)
    assert (view[:, :, tables.zero_row] == 0).all()


# ---------------------------------------------------------------------------
# SA sharded driver: halo node mode
# ---------------------------------------------------------------------------


def _sa_setup(n=60, d=3, R=4, L=2000, seed=5):
    g = random_regular_graph(n, d, seed=seed)
    rng = np.random.default_rng(seed + 1)
    s0 = (2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8)
    proposals = rng.integers(0, n, size=(R, L)).astype(np.int32)
    uniforms = rng.random(size=(R, L))
    return g, s0, proposals, uniforms


def test_sa_halo_bit_parity_vs_unsharded_and_gather():
    """node_mode='halo' chains are bit-identical to the unsharded solver
    AND the legacy gather-mode mesh solver under injected streams, across
    node-axis sizes (the parity triangle the GD013 disables cite)."""
    from graphdyn.models.sa import simulated_annealing
    from graphdyn.parallel.sa_sharded import sa_sharded

    g, s0, proposals, uniforms = _sa_setup()
    cfg = SAConfig()
    kw = dict(s0=s0, proposals=proposals, uniforms=uniforms)
    ref = simulated_annealing(g, cfg, **kw)
    for rep, node in ((4, 2), (2, 4), (1, 8)):
        halo = sa_sharded(g, cfg, mesh=_mesh(rep, node), node_mode="halo",
                          **kw)
        np.testing.assert_array_equal(halo.s, ref.s)
        np.testing.assert_array_equal(halo.num_steps, ref.num_steps)
        np.testing.assert_array_equal(halo.m_final, ref.m_final)
    gather = sa_sharded(g, cfg, mesh=_mesh(2, 4), **kw)
    halo = sa_sharded(g, cfg, mesh=_mesh(2, 4), node_mode="halo", **kw)
    np.testing.assert_array_equal(halo.s, gather.s)
    np.testing.assert_array_equal(halo.num_steps, gather.num_steps)


def test_sa_halo_ragged_graph_and_validation():
    """Ragged (ER) degrees ride the zero column correctly, and the mode
    guards fire: halo needs a node axis >= 2, refuses lightcone, and
    refuses a partition whose P mismatches the mesh."""
    from graphdyn.models.sa import simulated_annealing
    from graphdyn.parallel.sa_sharded import sa_sharded

    g = erdos_renyi_graph(59, 4.0 / 58, seed=3)
    rng = np.random.default_rng(4)
    R, L = 4, 600
    s0 = (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
    kw = dict(
        s0=s0,
        proposals=rng.integers(0, g.n, size=(R, L)).astype(np.int32),
        uniforms=rng.random(size=(R, L)),
        max_steps=500,
    )
    cfg = SAConfig()
    ref = simulated_annealing(g, cfg, **kw)
    got = sa_sharded(g, cfg, mesh=_mesh(2, 4), node_mode="halo", **kw)
    np.testing.assert_array_equal(got.s, ref.s)
    np.testing.assert_array_equal(got.num_steps, ref.num_steps)

    with pytest.raises(ValueError, match="node axis of size >= 2"):
        sa_sharded(g, cfg, mesh=_mesh(8, 1), node_mode="halo", **kw)
    with pytest.raises(ValueError, match="lightcone"):
        sa_sharded(g, cfg, mesh=_mesh(8, 1), node_mode="halo",
                   rollout_mode="lightcone", **kw)
    with pytest.raises(ValueError, match="P=2"):
        sa_sharded(g, cfg, mesh=_mesh(2, 4), node_mode="halo",
                   partition=partition_graph(g, 2), **kw)
    with pytest.raises(ValueError, match="node_mode"):
        sa_sharded(g, cfg, mesh=_mesh(2, 4), partition=partition_graph(g, 4),
                   **kw)


def test_sa_halo_resume_across_modes_and_shard_counts(tmp_path,
                                                      abort_after_save):
    """Snapshots are GLOBAL (layout-agnostic): a halo run interrupted
    mid-chain resumes bit-exactly under a different shard count AND under
    the legacy gather mode — the shard-loss requeue story at the driver
    level (a lost shard means the requeued run gets a different node-axis
    size; nothing in the snapshot remembers the old partition)."""
    from conftest import CheckpointAbort

    from graphdyn.parallel.sa_sharded import sa_sharded

    g, s0, proposals, uniforms = _sa_setup()
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    kw = dict(s0=s0, proposals=proposals, uniforms=uniforms)
    base = sa_sharded(g, cfg, mesh=_mesh(2, 4), node_mode="halo", **kw)

    # halo P=4 -> halo P=2 (simulated shard loss shrinks the pool)
    p1 = str(tmp_path / "halo_ck1")
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            sa_sharded(g, cfg, mesh=_mesh(2, 4), node_mode="halo",
                       checkpoint_path=p1, checkpoint_interval_s=0.0,
                       chunk_steps=37, **kw)
    assert os.path.exists(p1 + ".npz")
    resumed = sa_sharded(g, cfg, mesh=_mesh(4, 2), node_mode="halo",
                         checkpoint_path=p1, chunk_steps=64, **kw)
    np.testing.assert_array_equal(base.s, resumed.s)
    np.testing.assert_array_equal(base.num_steps, resumed.num_steps)
    np.testing.assert_array_equal(base.m_final, resumed.m_final)
    assert not os.path.exists(p1 + ".npz")

    # halo -> gather cross-mode resume (the snapshot is mode-agnostic)
    p2 = str(tmp_path / "halo_ck2")
    with abort_after_save(n=1):
        with pytest.raises(CheckpointAbort):
            sa_sharded(g, cfg, mesh=_mesh(2, 4), node_mode="halo",
                       checkpoint_path=p2, checkpoint_interval_s=0.0,
                       chunk_steps=41, **kw)
    resumed2 = sa_sharded(g, cfg, mesh=_mesh(4, 2), checkpoint_path=p2,
                          chunk_steps=5000, **kw)
    np.testing.assert_array_equal(base.s, resumed2.s)
    np.testing.assert_array_equal(base.num_steps, resumed2.num_steps)


def test_sa_halo_preempt_requeue_multihost_fault_journal(tmp_path):
    """The multihost resume contract across a simulated shard loss,
    end to end in one process: episode 1 (halo, P=4) is preempted by an
    injected SIGTERM-equivalent at a chunk boundary (the PR-2 `signal`
    action — race-free) and snapshots; the REQUEUED episode 2 comes up on
    a SHRUNK pool (P=2), hits the `multihost.init` fault site on its way
    up (the not-yet-recovered coordinator a real shard loss leaves
    behind; the driver degrades to single-process exactly as documented),
    resumes from the snapshot, and finishes BIT-EXACT to the fault-free
    oracle — with the PR-9 run journal validating and carrying both the
    preempted episode's save and the requeue's load."""
    from graphdyn.resilience import ShutdownRequested
    from graphdyn.resilience.faults import FaultPlan, FaultSpec
    from graphdyn.resilience.store import journal_path_for, validate_journal
    from graphdyn.parallel.sa_sharded import sa_sharded

    g, s0, proposals, uniforms = _sa_setup()
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    kw = dict(s0=s0, proposals=proposals, uniforms=uniforms)
    oracle = sa_sharded(g, cfg, mesh=_mesh(2, 4), node_mode="halo", **kw)

    ck = str(tmp_path / "mh" / "ck")
    with FaultPlan([FaultSpec("chunk.boundary", "signal", at=2)]):
        with pytest.raises(ShutdownRequested):
            sa_sharded(g, cfg, mesh=_mesh(2, 4), node_mode="halo",
                       checkpoint_path=ck, checkpoint_interval_s=0.0,
                       chunk_steps=31, **kw)
    assert os.path.exists(ck + ".npz")           # the preemption snapshot

    plan = FaultPlan([FaultSpec("multihost.init", count=1)])
    with plan:
        requeued = sa_sharded(g, cfg, mesh=_mesh(4, 2), node_mode="halo",
                              checkpoint_path=ck, chunk_steps=5000, **kw)
    assert plan.specs[0].hits == 1               # the halo path HIT the site
    np.testing.assert_array_equal(oracle.s, requeued.s)
    np.testing.assert_array_equal(oracle.num_steps, requeued.num_steps)
    np.testing.assert_array_equal(oracle.m_final, requeued.m_final)

    events, problems = validate_journal(journal_path_for(ck))
    assert problems == [], problems
    ops = [e.get("op") for e in events if e.get("ev") == "journal"]
    assert "save" in ops and "load" in ops       # preempt saved, requeue loaded


# ---------------------------------------------------------------------------
# CLI --shards
# ---------------------------------------------------------------------------


def test_cli_sa_shards_halo(tmp_path, capsys):
    from graphdyn.cli import main

    out = str(tmp_path / "sh.npz")
    rc = main([
        "sa", "--n", "64", "--d", "3", "--p", "1", "--c", "1",
        "--sharded", "--shards", "2", "--n-replicas", "3",
        "--max-steps", "4000", "--seed", "1", "--out", out,
    ])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["node_mode"] == "halo"
    assert line["mesh"]["node"] == 2
    assert os.path.exists(out)
    # --shards 1 stays on the single-shard gather path; bad values refuse
    rc = main(["sa", "--n", "64", "--d", "3", "--p", "1", "--c", "1",
               "--sharded", "--shards", "1", "--n-replicas", "2",
               "--max-steps", "2000", "--seed", "1"])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["node_mode"] == "gather" and line["mesh"]["node"] == 1
    with pytest.raises(SystemExit, match="lightcone"):
        main(["sa", "--n", "64", "--sharded", "--shards", "2",
              "--rollout-mode", "lightcone"])
    with pytest.raises(SystemExit, match="shards"):
        main(["sa", "--n", "64", "--sharded", "--shards", "0"])


@pytest.mark.slow
def test_cli_shards_preempt_requeue_subprocess(tmp_path, multi_device_cpu):
    """The PR-10 requeue contract across REAL process boundaries on the
    forced 8-device CPU platform (the multi_device_cpu fixture): a halo
    --shards run preempted by an injected signal exits 75 with a
    snapshot; rerunning the same command line (what a scheduler's requeue
    does) — on FEWER shards, simulating the lost one — resumes and
    produces the oracle's exact result."""
    from graphdyn.utils.io import load_results_npz

    ck = str(tmp_path / "ck" / "run")
    argv = ["sa", "--n", "64", "--d", "3", "--p", "1", "--c", "1",
            "--n-replicas", "3", "--max-steps", "4000", "--seed", "1",
            "--sharded"]
    ckpt = ["--checkpoint", ck, "--checkpoint-interval", "0",
            "--chunk-steps", "500"]

    oracle = multi_device_cpu(
        argv + ["--shards", "4", "--out", str(tmp_path / "oracle.npz")],
    )
    assert oracle.returncode == 0, oracle.stderr[-2000:]

    plan = json.dumps(
        [{"site": "chunk.boundary", "action": "signal", "at": 1}]
    )
    ep1 = multi_device_cpu(
        argv + ckpt + ["--shards", "4"], env={"GRAPHDYN_FAULT_PLAN": plan},
    )
    assert ep1.returncode == 75, (ep1.returncode, ep1.stderr[-2000:])
    assert os.path.exists(ck + ".npz")

    ep2 = multi_device_cpu(
        argv + ckpt + ["--shards", "2",
                       "--out", str(tmp_path / "requeued.npz")],
    )
    assert ep2.returncode == 0, ep2.stderr[-2000:]
    a = load_results_npz(str(tmp_path / "oracle.npz"))
    b = load_results_npz(str(tmp_path / "requeued.npz"))
    np.testing.assert_array_equal(a["conf"], b["conf"])
    np.testing.assert_array_equal(a["num_steps"], b["num_steps"])


# ---------------------------------------------------------------------------
# bench row contract
# ---------------------------------------------------------------------------


def test_bench_halo_weak_scaling_contract(monkeypatch):
    """The measured path (this harness forces 8 devices): per-P rates,
    P=1 = the unsharded program, a positive efficiency and the byte
    model's exchange traffic. Tiny override shapes keep it tier-1."""
    import bench

    row = bench.halo_weak_scaling(True, n_per=256, R=64, steps=4, iters=1)
    assert row["halo_weak_efficiency"] > 0
    rates = row["halo_rate_by_shards"]
    assert set(rates) == {"1", "2", "4", "8"}
    assert all(v > 0 for v in rates.values())
    assert row["halo_bytes_per_step"] > 0
    assert row["halo_workload"]["P_max"] == 8


def test_bench_halo_weak_scaling_null_reason_single_device(monkeypatch):
    """Fewer than 2 devices -> null + reason, never 0.0 (the benchcheck
    contract)."""
    import bench

    import jax

    real_devices = jax.devices

    def one_device(*args):
        return real_devices()[:1]

    monkeypatch.setattr(jax, "devices", one_device)
    row = bench.halo_weak_scaling(True)
    assert row["halo_weak_efficiency"] is None
    assert ">= 2 devices" in row["halo_weak_efficiency_skipped_reason"]
    assert row["halo_bytes_per_step"] is None
    assert row["halo_bytes_per_step_skipped_reason"]


# ---------------------------------------------------------------------------
# hub splitting: vertex-cut replicated hubs (ISSUE 18)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("P", [2, 4])
def test_halo_hub_split_bit_exact(P):
    """packed_rollout(partition=) with hub-split partitions equals the
    unsharded program bitwise on a seeded power-law — the hub's popcount
    is accumulated from per-shard partials over the ring, and partial
    CSA/integer addition is exact, so any divergence is a replication or
    ring bug, not roundoff."""
    from graphdyn.graphs import powerlaw_graph

    g = powerlaw_graph(400, gamma=2.3, dmin=2, seed=5)
    part = partition_graph(g, P, seed=0, hub_threshold=32)
    assert part.hubs is not None and part.hubs.size > 0
    assert (g.deg[part.hubs] >= 32).all()
    tables = build_halo_tables(g, part)
    assert tables.n_hubs == part.hubs.size
    # the ring ships a bounded O(P·H·log dmax) payload per step
    assert tables.hub_ring_words > 0
    rng = np.random.default_rng(1)
    s = (2 * rng.integers(0, 2, size=(64, g.n)) - 1).astype(np.int8)
    sp = pack_spins(s)
    nbr, deg = jnp.asarray(g.nbr), jnp.asarray(g.deg)
    for rule, tie in (("majority", "stay"), ("minority", "change")):
        ref = np.asarray(packed_rollout(
            nbr, deg, jnp.asarray(sp), 12, rule, tie))
        got = np.asarray(packed_rollout(
            nbr, deg, jnp.asarray(sp), 12, rule, tie, partition=part))
        np.testing.assert_array_equal(got, ref, err_msg=f"P={P} {rule}")


@pytest.mark.parametrize("P", [2, 4])
def test_halo_wide_hub_segment_path_bit_exact(P):
    """Per-shard hub slices wider than UNROLL_MAX take the ops/bucketed
    segment-reshape popcount (program size O(log d_hub), not one unrolled
    add per neighbor slot) — same bits as the unsharded kernel. The tiny
    hubs of the seeded power-law tests never leave the unrolled path, so
    this graph forces one genuine big hub: degree 160, slices of ~160/P
    neighbors per shard."""
    from graphdyn.graphs import from_edgelist
    from graphdyn.ops.bucketed import UNROLL_MAX

    n = 200
    edges = [(0, v) for v in range(1, 161)]
    edges += [(u, u + 1) for u in range(1, n - 1)] + [(n - 1, 1)]
    g = from_edgelist(np.array(edges, np.int64), n=n)
    assert int(g.deg[0]) == 160
    part = partition_graph(g, P, seed=0, hub_threshold=32)
    assert part.hubs is not None and 0 in part.hubs
    tables = build_halo_tables(g, part)
    hd_max = tables.hub_nbr_loc.shape[2]
    assert hd_max > UNROLL_MAX and hd_max % UNROLL_MAX == 0
    rng = np.random.default_rng(3)
    s = (2 * rng.integers(0, 2, size=(64, n)) - 1).astype(np.int8)
    sp = pack_spins(s)
    nbr, deg = jnp.asarray(g.nbr), jnp.asarray(g.deg)
    for rule, tie in (("majority", "stay"), ("minority", "change")):
        ref = np.asarray(packed_rollout(
            nbr, deg, jnp.asarray(sp), 10, rule, tie))
        got = np.asarray(packed_rollout(
            nbr, deg, jnp.asarray(sp), 10, rule, tie, partition=part))
        np.testing.assert_array_equal(got, ref, err_msg=f"P={P} {rule}")


def test_halo_hub_split_layout_and_controls():
    """The hub-split layout contract: hubs are owned by no part, the
    owned-row gather width shrinks to the non-hub max degree, and a
    hubless partition of the same graph keeps hub tables empty (the
    fast-path predicate, not graph class, decides)."""
    from graphdyn.graphs import powerlaw_graph

    g = powerlaw_graph(400, gamma=2.3, dmin=2, seed=5)
    part = partition_graph(g, 4, seed=0, hub_threshold=32)
    assert (part.part[part.hubs] == -1).all()
    assert np.array_equal(
        np.sort(np.concatenate([part.order, part.hubs])), np.arange(g.n))
    tables = build_halo_tables(g, part)
    hub_mask = np.zeros(g.n, bool)
    hub_mask[part.hubs] = True
    assert tables.nbr_loc.shape[2] == int(g.deg[~hub_mask].max())
    assert tables.nbr_loc.shape[2] < g.dmax
    # hubless control on the SAME graph: no hub tables, no ring
    plain = partition_graph(g, 4, seed=0)
    assert plain.hubs is None
    t2 = build_halo_tables(g, plain)
    assert t2.n_hubs == 0 and t2.hub_ring_words == 0
    # the int8 SA halo layout replicates every hub's spin into EVERY
    # shard's hub columns (the vertex-cut invariant) and round-trips
    s = (2 * np.random.default_rng(8).integers(0, 2, size=(3, g.n)) - 1) \
        .astype(np.int8)
    cols = sa_halo_cols(tables, s)
    view = cols.reshape(3, tables.P, tables.n_rows)
    h0 = tables.hub_row0
    for p in range(tables.P):
        np.testing.assert_array_equal(
            view[:, p, h0:h0 + tables.n_hubs], s[:, tables.hub_global])
    np.testing.assert_array_equal(sa_halo_uncols(tables, cols), s)


@pytest.mark.parametrize("P", [2, 4])
def test_sa_halo_hub_split_bit_parity(P):
    """The sharded SA chain over a hub-split partition is bit-identical
    to the unsharded solver — in PRNG mode AND under injected streams.
    The load-bearing step is proposal propagation: a hub flip must land
    in the replicated hub columns of EVERY shard before the candidate
    rollout reads any of them, and the injected stream is sized so many
    in-run proposals actually hit hubs (asserted, not hoped)."""
    from graphdyn.models.sa import simulated_annealing
    from graphdyn.parallel.sa_sharded import sa_sharded

    from graphdyn.graphs import powerlaw_graph

    n, R = 96, 8
    g = powerlaw_graph(n, gamma=2.2, dmin=2, seed=5)
    thr = int(np.sort(g.deg)[-4])
    hubs = np.flatnonzero(g.deg >= thr)
    part = partition_graph(g, P, seed=0, hub_threshold=thr)
    assert part.hubs is not None and part.hubs.size > 0
    cfg = SAConfig()
    mesh = _mesh(8 // P, P)

    # PRNG mode: chains run to convergence or timeout
    ref = simulated_annealing(g, cfg, n_replicas=R, seed=11,
                              max_steps=4000, layout="padded")
    got = sa_sharded(g, cfg, mesh=mesh, n_replicas=R, seed=11,
                     max_steps=4000, node_mode="halo", partition=part)
    np.testing.assert_array_equal(got.s, ref.s)
    np.testing.assert_array_equal(got.num_steps, ref.num_steps)
    np.testing.assert_array_equal(got.m_final, ref.m_final)

    # injected streams: the proposal sequence provably exercises hubs
    rng = np.random.default_rng(2)
    L = 512
    kw = dict(
        s0=(2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8),
        proposals=rng.integers(0, n, size=(R, L)).astype(np.int32),
        uniforms=rng.random(size=(R, L)),
        max_steps=L,
    )
    ref = simulated_annealing(g, cfg, n_replicas=R, seed=0,
                              layout="padded", **kw)
    hub_props = sum(
        int(np.isin(kw["proposals"][r, :int(ref.num_steps[r])], hubs).sum())
        for r in range(R)
    )
    assert hub_props > 10, "stream never proposed a hub — dead test"
    got = sa_sharded(g, cfg, mesh=mesh, n_replicas=R, seed=0,
                     node_mode="halo", partition=part, **kw)
    np.testing.assert_array_equal(got.s, ref.s)
    np.testing.assert_array_equal(got.num_steps, ref.num_steps)
    np.testing.assert_array_equal(got.m_final, ref.m_final)


def test_partition_hub_threshold_validation():
    g = random_regular_graph(64, 3, seed=0)
    with pytest.raises(ValueError, match="hub_threshold"):
        partition_graph(g, 2, hub_threshold=0)
    # a threshold above dmax is a no-op: hubless partition
    part = partition_graph(g, 2, seed=0, hub_threshold=1000)
    assert part.hubs is None or part.hubs.size == 0
