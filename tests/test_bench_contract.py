"""The driver's contract with bench.py: stdout is exactly ONE JSON line with
the headline metric fields (the round artifact `BENCH_r{N}.json` is parsed
from it). A formatting regression here silently voids a whole round's
benchmark, so the contract is pinned as a test (smoke shapes, forced-CPU
subprocess — the same invocation path the driver uses)."""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_emits_one_json_line():
    env = dict(os.environ)
    env["GRAPHDYN_FORCE_PLATFORM"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=560, cwd=ROOT, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got {lines!r}"
    row = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "backend",
                "packed_rate_natural_order", "packed_rate_bfs_order",
                "int8_rate", "torch_cpu_rate"):
        assert key in row, key
    assert row["value"] > 0
    assert row["unit"] == "spin-updates/s"
    # the smoke row must not carry the full-shape-only roofline fraction
    assert "roofline_fraction_v5e" not in row
