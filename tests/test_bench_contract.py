"""The driver's contract with bench.py: stdout is exactly ONE JSON line with
the headline metric fields (the round artifact `BENCH_r{N}.json` is parsed
from it). A formatting regression here silently voids a whole round's
benchmark, so the contract is pinned as a test (smoke shapes, forced-CPU
subprocess — the same invocation path the driver uses)."""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_emits_one_json_line():
    env = dict(os.environ)
    env["GRAPHDYN_FORCE_PLATFORM"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), "--smoke"],
        capture_output=True, text=True, timeout=720, cwd=ROOT, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"stdout must be ONE JSON line, got {lines!r}"
    row = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "backend",
                "packed_rate_natural_order", "packed_rate_bfs_order",
                "int8_rate", "torch_cpu_rate"):
        assert key in row, key
    assert row["value"] > 0
    assert row["unit"] == "spin-updates/s"
    # the smoke row must not carry the full-shape-only roofline fraction
    assert "roofline_fraction_v5e" not in row
    # rows skipped on this backend are null + reason, NEVER 0.0 (a skip
    # must be unmistakable from a measured collapse)
    for key in ("packed_rate_wide", "packed_rate_pallas",
                "entropy_cell_rate_pallas"):
        assert row[key] is None, (key, row[key])
        assert "chip-only" in row[key + "_skipped_reason"]
    # the human-readable progress log honors the same contract: a skipped
    # row says skipped(<reason>), never a 0.000e+00 rate
    assert "rate 0.000e+00" not in proc.stderr
    assert "rate skipped(" in proc.stderr
    # the end-to-end driver A/B: the grouped pipeline must beat the serial
    # repetition loop on the same workload (results are element-wise
    # identical — tests/test_pipeline.py), and the ratio is recorded
    assert row["ensemble_rate"] > 0
    assert row["ensemble_rate_serial"] > 0
    assert row["ensemble_speedup"] > 1.0, row["ensemble_speedup"]
    # the graftcheck structural summary rides in every round's row (or is
    # an explicit null + reason — never silently absent), so benchcheck
    # can diff op/fusion counts round-over-round even in no-TPU rounds
    assert "fingerprints" in row
    fp = row["fingerprints"]
    if fp is None:
        assert row["fingerprints_skipped_reason"]
    else:
        assert fp["backend"] == "cpu"
        from graphdyn.analysis.graftcheck import ENTRIES, _COMPACT_FIELDS

        assert set(fp["entries"]) == set(ENTRIES)
        for entry_fp in fp["entries"].values():
            assert set(entry_fp) == set(_COMPACT_FIELDS)
    # the obs telemetry columns: every round names its event ledger (path +
    # manifest hash) or carries an explicit null + reason — never silent
    assert "obs_ledger" in row
    if row["obs_ledger"] is None:
        assert row["obs_ledger_skipped_reason"]
    else:
        assert row["obs_manifest_sha"]
        from graphdyn.obs.recorder import read_ledger

        events, torn = read_ledger(row["obs_ledger"])
        assert torn == 0
        man = next(e for e in events if e["ev"] == "manifest")
        assert man["run"]["cmd"] == "bench" and man["run"]["backend"] == "cpu"
        # the bench timing brackets are obs spans now — they land in the
        # round's own ledger
        spans = {e["name"] for e in events if e["ev"] == "span"}
        assert "bench.packed_rate" in spans and "bench.int8_rate" in spans
    # the derived cost-model columns (graftcost ledger models evaluated at
    # the bench size): positive values, or an explicit null + reason —
    # never zeros, never silently absent
    for col in ("derived_bytes", "arithmetic_intensity"):
        assert col in row, col
        if row[col] is None:
            assert row[col + "_skipped_reason"], col
        else:
            assert row[col] > 0, (col, row[col])
    # the durable-store save-overhead column (interleaved p50/p99 A/B of
    # DurableCheckpoint.save vs raw Checkpoint.save): a measured ratio or
    # an explicit null + reason — never silently absent
    assert "ckpt_save_overhead" in row
    cso = row["ckpt_save_overhead"]
    if cso is None:
        assert row["ckpt_save_overhead_skipped_reason"]
    else:
        assert cso["overhead_p50_x"] > 0
        assert cso["raw_p50_s"] > 0 and cso["durable_p50_s"] > 0
        assert cso["raw_p99_s"] > 0 and cso["durable_p99_s"] > 0
        assert cso["snapshot_bytes"] > 0 and cso["saves"] > 0
    # the liveness-tax column (interleaved watchdog-on/off A/B of the
    # entropy smoke workload): a measured ratio with a positive heartbeat
    # count, or an explicit null + reason — never silently absent
    assert "heartbeat_overhead" in row
    hbo = row["heartbeat_overhead"]
    if hbo is None:
        assert row["heartbeat_overhead_skipped_reason"]
    else:
        assert hbo["overhead_p50_x"] > 0
        assert hbo["off_p50_s"] > 0 and hbo["on_p50_s"] > 0
        assert hbo["beats_per_run"] > 0 and hbo["runs"] > 0
    # the serve rows: multi-tenant bucket hit rate and end-to-end job
    # latency through the real serve worker — measured positive values,
    # or an explicit null + reason — never silently absent, never 0.0
    assert "serve_bucket_hit_rate" in row
    sbh = row["serve_bucket_hit_rate"]
    if sbh is None:
        assert row["serve_bucket_hit_rate_skipped_reason"]
    else:
        assert sbh["hit_rate"] > 0
        assert sbh["jobs"] > 0 and sbh["misses"] > 0
    assert "serve_job_latency" in row
    sjl = row["serve_job_latency"]
    if sjl is None:
        assert row["serve_job_latency_skipped_reason"]
    else:
        assert sjl["warm_p50_s"] > 0 and sjl["cold_p50_s"] > 0
        assert sjl["warm_p99_s"] > 0 and sjl["cold_p99_s"] > 0
        assert sjl["cold_over_warm_p50_x"] > 0 and sjl["jobs"] > 0
    # the power-law bucketed-layout row (degree-bucketed rollout vs the
    # equal-edge padded RRG control): a measured positive rate with its
    # control detail, or an explicit null + reason — never 0.0
    assert "powerlaw_rate" in row
    plr = row["powerlaw_rate"]
    if plr is None:
        assert row["powerlaw_rate_skipped_reason"]
    else:
        assert plr > 0
        det = row["powerlaw_rate_detail"]
        assert det["rrg_padded_rate"] > 0
        assert det["rrg_over_bucketed_x"] > 0
        assert det["hub_degree"] > 0 and det["table_entries"] > 0
        # the whole point of the layout: resident table bytes follow E,
        # not n·dmax — the bucketed table must beat the padded one
        assert det["table_entries"] < det["padded_entries"]
    # the out-of-core streamed rows: overlapped chunk-gather rate on an
    # adjacency exceeding the clamped budget (with the forced-synchronous
    # A/B leg in the detail) and the live edge-churn rate with the
    # rollout still advancing — null-or-positive, never 0.0
    assert "stream_rate" in row
    if row["stream_rate"] is None:
        assert row["stream_rate_skipped_reason"]
    else:
        assert row["stream_rate"] > 0
        det = row["stream_rate_detail"]
        assert det["sync_rate"] > 0
        # the row only exists in the streaming regime: the plan must have
        # chunked under a budget strictly below the resident model
        assert det["chunks"] >= 2
        assert det["device_budget_bytes"] < det["resident_model_bytes"]
        assert 0.0 <= det["overlap_frac"] <= 1.0
    assert "churn_rate" in row
    if row["churn_rate"] is None:
        assert row["churn_rate_skipped_reason"]
    else:
        assert row["churn_rate"] > 0
        det = row["churn_rate_detail"]
        assert det["applied_mutations"] > 0
        assert det["spin_update_rate"] > 0
    # the sharded streamed rows (PR 20): weak-scaling efficiency of the
    # composed chunk-walk × halo-exchange engine, and the live
    # churn-driven repartition drive — null-or-positive, never 0.0
    assert "stream_shard_efficiency" in row
    if row["stream_shard_efficiency"] is None:
        assert row["stream_shard_efficiency_skipped_reason"]
    else:
        assert row["stream_shard_efficiency"] > 0
        rates = row["stream_shard_rate_by_shards"]
        assert rates["1"] > 0
        assert all(v > 0 for v in rates.values())
    assert "churn_repartition_rate" in row
    if row["churn_repartition_rate"] is None:
        assert row["churn_repartition_rate_skipped_reason"]
    else:
        assert row["churn_repartition_rate"] > 0
        det = row["churn_repartition_rate_detail"]
        assert det["applied_mutations"] > 0
        assert det["spin_update_rate"] > 0
        assert det["shards"] == 2
    # the device-memory column: a positive peak, or an explicit null +
    # reason (CPU: no usable memory_stats) — never silently absent,
    # never a fake 0 (graphdyn.obs.memband.peak_hbm_bytes)
    assert "peak_hbm_bytes" in row
    if row["peak_hbm_bytes"] is None:
        assert row["peak_hbm_bytes_skipped_reason"]
    else:
        assert row["peak_hbm_bytes"] > 0
    # the time-to-target search rows (tta_tempering / tta_chromatic): a
    # measured speedup + a NONZERO swap acceptance rate, or an explicit
    # null + reason — never 0.0, and never a dead ladder benched as fast
    for key in ("tta_tempering", "tta_chromatic"):
        assert key in row, key
        if row[key] is None:
            assert row[key + "_skipped_reason"], key
        else:
            assert row[key]["speedup_x"] > 0
            assert row[key]["device_steps"] > 0
    assert "swap_acceptance_rate" in row
    if row["tta_tempering"] is not None:
        assert row["swap_acceptance_rate"] > 0
    # the fused one-kernel annealer rows: tta_fused measures on CPU too
    # (device-step counts, seed-deterministic), fused_sa_rate is chip-only
    # — both null-or-positive, never 0.0
    assert "tta_fused" in row
    if row["tta_fused"] is None:
        assert row["tta_fused_skipped_reason"]
    else:
        assert row["tta_fused"]["speedup_x"] > 0
        assert row["tta_fused"]["device_steps"] > 0
        assert row["tta_fused"]["kernel"] in (
            "xla", "pallas", "pallas-interpret")
    assert "fused_sa_rate" in row
    if row["fused_sa_rate"] is None:
        assert "chip-only" in row["fused_sa_rate_skipped_reason"]
    else:
        assert row["fused_sa_rate"] > 0
    # the rider A/B (saved per-chunk sync) rides with measured tta legs
    if row["tta_tempering"] is not None:
        sab = row["tta_fixed_budget_sync"]
        assert sab["sync_s"] > 0 and sab["nosync_s"] > 0
        assert sab["sync_saved_x"] > 0
    # the cross-round rate trend gate RAN (or was explicitly skipped) and
    # found no unblessed drift — the benchcheck contract
    status = row.get("obs_trend_status")
    if status in (None, "skipped"):
        assert row.get("obs_trend_skipped_reason"), row
    else:
        assert status in ("stable", "blessed", "no_baseline"), (
            status, row.get("obs_trend_findings"))


def test_bench_emits_partials_on_midrun_failure(monkeypatch, capsys):
    """A device failure mid-run must still produce the single JSON line,
    carrying the rates measured before the failure (the r04 wedge lost a
    27-minute session to a bare traceback — never again)."""
    import bench

    calls = {"k": 0}

    def flaky(g, R, steps, iters=3):
        calls["k"] += 1
        if calls["k"] >= 2:              # natural-order succeeds, BFS dies
            raise RuntimeError("simulated tunnel wedge")
        return 1.0e6                     # the contract cares only that a
        #                                  positive partial rate was recorded

    monkeypatch.setattr(bench, "packed_rate", flaky)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--smoke"])
    # skip the relay probe loop (the probe requires a chip backend, which
    # the hermetic CPU suite never has — without the force it would burn
    # the full probe budget before falling back)
    monkeypatch.setenv("GRAPHDYN_FORCE_PLATFORM", "cpu")
    rc = bench.main()
    assert rc == 0                        # partial rates exist => usable row
    lines = [ln for ln in capsys.readouterr().out.splitlines() if ln.strip()]
    assert len(lines) == 1
    row = json.loads(lines[0])
    assert "simulated tunnel wedge" in row["error"]
    assert row["value"] == row["packed_rate_natural_order"] > 0
    assert row["packed_rate_bfs_order"] == 0.0


def test_device_draw_helpers_sharded():
    """draw_u32 / draw_pm1_int8 land directly in the requested sharding
    (the config-5 path: the state never exists on the host)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    import numpy as np

    from benchmarks.common import draw_pm1_int8, draw_u32
    from graphdyn.parallel.mesh import make_mesh

    mesh = make_mesh((8,), ("replica",))
    sh = NamedSharding(mesh, P("replica"))
    s = draw_pm1_int8(0, (16, 64), out_shardings=sh)
    assert s.sharding.is_equivalent_to(sh, 2)
    assert set(np.unique(np.asarray(s))) <= {-1, 1}
    w = draw_u32(1, (16, 8), out_shardings=sh)
    assert w.sharding.is_equivalent_to(sh, 2)
    assert w.dtype == np.uint32


def test_bench_smoke_entropy_cell_row(monkeypatch, capsys):
    """The entropy cell-ladder A/B row obeys the skip contract in-process:
    a measured rate is positive, a skip is null + reason — NEVER 0.0. The
    live subprocess run above carries whichever form this host measures."""
    import bench

    out = bench.entropy_cell_rate(smoke=True)
    assert "entropy_cell_rate" in out
    if out["entropy_cell_rate"] is None:
        assert out["entropy_cell_rate_skipped_reason"]
        assert out["entropy_cell_speedup_measured"] > 0
    else:
        assert out["entropy_cell_rate"] > 0
        assert out["entropy_cell_speedup"] >= 1.2
    assert out["entropy_cell_workload"]["lambda_points"] > 0
    # the grouped-Pallas A/B column: chip-only, null + reason elsewhere,
    # and the kernel tag names each leg's sweep core
    assert "entropy_cell_rate_pallas" in out
    if out["entropy_cell_rate_pallas"] is None:
        assert out["entropy_cell_rate_pallas_skipped_reason"]
    else:
        assert out["entropy_cell_rate_pallas"] > 0
        assert out["entropy_cell_pallas_speedup"] > 0
    kern = out["entropy_cell_workload"]["kernel"]
    assert kern["serial"] == "xla" and kern["grouped"] == "xla"


def test_bench_heartbeat_overhead_contract():
    """The liveness A/B in-process: the workload actually heartbeats
    (beats_per_run > 0) and the watchdog-on leg measures a real, positive
    ratio — supervision must be near-free, and the row is how a regression
    in that claim would surface round-over-round."""
    import bench

    out = bench.heartbeat_overhead(smoke=True)
    hbo = out["heartbeat_overhead"]
    assert hbo["beats_per_run"] > 0
    assert hbo["off_p50_s"] > 0 and hbo["on_p50_s"] > 0
    assert hbo["overhead_p50_x"] > 0
    # "near-free" with generous headroom for a noisy 2-core container: a
    # watchdog that made the workload 1.5x slower is a real regression
    assert hbo["overhead_p50_x"] < 1.5, hbo
    # the A/B must leave no pending shutdown behind (the watchdog never
    # fired with its 60s stall timeout)
    from graphdyn.resilience.shutdown import shutdown_requested

    assert not shutdown_requested()


def test_probe_relay_plugin_presence_classification(monkeypatch):
    """probe_relay distinguishes 'no PJRT plugin registered' (terminal —
    three fast failures stop the probe) from 'plugin present but init
    failed' (transient — a bouncing relay; keep probing until the budget
    runs out instead of misclassifying the window as no-chip)."""
    import subprocess
    import types

    from benchmarks import common

    calls = {"n": 0}

    def fake_run_plugin_present(cmd, **kw):
        calls["n"] += 1
        return types.SimpleNamespace(
            returncode=1, stdout="PROBE_PLUGINS axon\n",
            stderr="relay bounced",
        )

    monkeypatch.setattr(common.time, "sleep", lambda s: None)
    monkeypatch.setattr(subprocess, "run", fake_run_plugin_present)
    t0 = common.time.monotonic()
    assert common.probe_relay(0.5, probe_timeout=20.0) is False
    # fast failures with a plugin present burned the BUDGET (many retries),
    # never the three-strikes terminal path
    assert calls["n"] >= 3
    assert common.time.monotonic() - t0 < 10.0

    calls["n"] = 0

    def fake_run_no_plugin(cmd, **kw):
        calls["n"] += 1
        return types.SimpleNamespace(
            returncode=1, stdout="PROBE_PLUGINS -\n", stderr="no plugin",
        )

    monkeypatch.setattr(subprocess, "run", fake_run_no_plugin)
    assert common.probe_relay(1e9, probe_timeout=20.0) is False
    assert calls["n"] == 3          # terminal after three strikes

    def fake_run_chip_up(cmd, **kw):
        return types.SimpleNamespace(
            returncode=0, stdout="PROBE_PLUGINS axon\nPROBE_OK tpu\n",
            stderr="",
        )

    monkeypatch.setattr(subprocess, "run", fake_run_chip_up)
    assert common.probe_relay(5.0) is True

    def fake_run_cpu_only(cmd, **kw):
        return types.SimpleNamespace(
            returncode=0, stdout="PROBE_PLUGINS -\nPROBE_OK cpu\n",
            stderr="",
        )

    monkeypatch.setattr(subprocess, "run", fake_run_cpu_only)
    assert common.probe_relay(1e9) is False    # deterministic no-chip
