"""The chaos soak harness in tier-1 (bounded mode).

The acceptance criterion, verbatim: ≥ 6 composed-fault scenarios × ≥ 3
seeds, each ending in bit-exact parity with a fault-free oracle and a
schema-valid journal; a seeded ``checkpoint.bitrot`` injection detected on
load 100% of the time; a primary-directory loss mid-chain resumed from the
mirror. ``scripts/lint.sh``'s soakcheck step runs the same bounded matrix
standalone (``python -m graphdyn.resilience.soak --bounded``);
``GRAPHDYN_SKIP_SOAKCHECK=1`` (set by the lint-gate test) avoids running it
twice in-suite.
"""

import pytest

from graphdyn.resilience.soak import BOUNDED_SEEDS, SCENARIOS, main, run_soak

pytestmark = [pytest.mark.faultinject, pytest.mark.soak]


def test_scenario_catalogue_shape():
    """The catalogue covers the acceptance surface: ≥ 6 scenarios, the
    bitrot-detection and primary-loss-mirror stories among them, at least
    one mirror-configured workload, and the PR-10 supervision stories
    (stall detection, deadline preemption, crash-loop quarantine)."""
    assert len(SCENARIOS) >= 12
    assert {"bitrot", "mirror_failover", "mirror_degraded",
            "truncated_read", "torn_write", "requeue_storm",
            "hang_detect", "deadline_preempt",
            "crash_loop_quarantine", "race_mirror_exit",
            "race_prefetch_close", "stream_shard_requeue"} <= set(SCENARIOS)
    # the sharded-stream requeue story must assert the live-repartition
    # journal evidence, not just the churn replay
    assert {"stream.churn", "stream.repartition"} <= set(
        SCENARIOS["stream_shard_requeue"].require_ops)
    assert SCENARIOS["mirror_failover"].mirror
    assert SCENARIOS["hang_detect"].mode == "hang"
    assert SCENARIOS["crash_loop_quarantine"].mode == "crash_loop"
    # the graftrace seeded-schedule race scenarios (PR: host-concurrency
    # auditor): the mirror one must assert the write-behind journal story
    assert SCENARIOS["race_mirror_exit"].mode == "race_mirror"
    assert "mirror.save" in SCENARIOS["race_mirror_exit"].require_ops
    assert SCENARIOS["race_prefetch_close"].mode == "race_prefetch"
    assert ("supervise.stall_detected"
            in SCENARIOS["hang_detect"].require_flight)
    assert ("supervise.deadline"
            in SCENARIOS["deadline_preempt"].require_flight)
    assert ("supervise.quarantine"
            in SCENARIOS["crash_loop_quarantine"].require_ops)
    assert len(BOUNDED_SEEDS) >= 3


def test_bounded_soak_matrix_is_green(tmp_path):
    """The full bounded matrix: every (scenario, seed) run survives its
    composed-fault schedule with bit-exact oracle parity, a schema-valid
    journal carrying the scenario's required ops, and the per-episode
    flight-recorder story (post-mortem on preemption, none on a clean
    finish)."""
    report = run_soak(root=str(tmp_path / "soak"))
    assert report["scenarios"] >= 10 and report["seeds"] >= 3
    bad = [(r["scenario"], r["seed"], r["problems"])
           for r in report["runs"] if not r["ok"]]
    assert not bad, bad
    # the detection guarantees actually fired somewhere in the matrix
    by_name = {}
    for r in report["runs"]:
        by_name.setdefault(r["scenario"], []).append(r)
    for r in by_name["bitrot"]:
        assert "quarantine" in r["journal_ops"], r
    for r in by_name["mirror_failover"]:
        assert "failover" in r["journal_ops"], r
    # PR-10 supervision guarantees: the watchdog restarted a stalled run,
    # and the crash loop was quarantined rather than retried forever
    for r in by_name["hang_detect"]:
        assert "supervise.restart" in r["journal_ops"], r
    for r in by_name["crash_loop_quarantine"]:
        assert "supervise.quarantine" in r["journal_ops"], r
    # the sharded requeue really changed shard count AND saw the live
    # repartition (this harness forces 8 devices, so never the skip path)
    for r in by_name["stream_shard_requeue"]:
        assert not r.get("skipped"), r
        assert "stream.repartition" in r["journal_ops"], r
        assert list(r["episodes"][-1]["post_args"]) == ["--shards", "2"], r


def test_soak_cli_list_and_unknown_scenario(capsys):
    assert main(["--list"]) == 0
    out = capsys.readouterr().out
    for name in SCENARIOS:
        assert name in out
    with pytest.raises(SystemExit):
        main(["--scenarios", "no_such_scenario"])
