"""Supervised execution (ARCHITECTURE.md "Supervised execution"): heartbeat
plumbing, the watchdog's stall/deadline escalation ladder, the `stall` fault
action, and the supervise() restart loop's exit-code policy — quarantine
after N same-site crashes, seeded-jitter backoff, journaled episodes.

The end-to-end proofs (stall injected mid-run → watchdog preempt →
supervisor auto-restart → bit-exact finish; crash loop → quarantine) live in
the soak matrix (tests/test_soak.py, scenarios hang_detect /
deadline_preempt / crash_loop_quarantine); this file pins the units those
scenarios compose."""

import json
import os
import time

import pytest

from graphdyn.obs import flight
from graphdyn.resilience import faults as _faults
from graphdyn.resilience import supervisor as sup
from graphdyn.resilience.retry import RetryPolicy
from graphdyn.resilience.shutdown import clear_shutdown, shutdown_requested
from graphdyn.resilience.store import JOURNAL_NAME, validate_journal

pytestmark = pytest.mark.faultinject


@pytest.fixture(autouse=True)
def _clean_shutdown_flag():
    clear_shutdown()
    yield
    clear_shutdown()


# ---------------------------------------------------------------------------
# heartbeats
# ---------------------------------------------------------------------------


def test_beat_is_monotonic_and_readable():
    n0, t0, _ = sup.last_beat()
    n1 = sup.beat("chunk")
    n2 = sup.beat("rep")
    assert n2 == n1 + 1 > n0
    n, t, where = sup.last_beat()
    assert n == n2 and t >= t0 and where == "rep"


def test_beat_gauge_lands_in_flight_ring():
    flight.clear()
    sup.beat("lambda")
    beats = [e for e in flight.snapshot()
             if e.get("name") == "obs.heartbeat"]
    assert beats, "heartbeat gauge never reached the flight ring"
    assert beats[-1]["attrs"]["where"] == "lambda"
    assert beats[-1]["value"] == sup.last_beat()[0]


def test_crash_event_names_last_heartbeat(tmp_path, monkeypatch):
    """The flight post-mortem's obs.crash event carries the last heartbeat
    (count/boundary/age) even if the ring rotated the heartbeat gauges out
    — a crash always names the last boundary the run crossed."""
    monkeypatch.chdir(tmp_path)
    sup.beat("lambda")
    flight.clear()                      # the ring has NO heartbeat events
    path = flight.dump("exception", exc=RuntimeError("boom"))
    assert path is not None
    from graphdyn.obs.recorder import read_ledger

    events, _ = read_ledger(path)
    crash = [e for e in events if e.get("name") == "obs.crash"][-1]
    assert crash["attrs"]["heartbeat_where"] == "lambda"
    assert crash["attrs"]["heartbeat_n"] == sup.last_beat()[0]
    assert crash["attrs"]["heartbeat_age_s"] >= 0


def test_raise_if_requested_beats():
    from graphdyn.resilience.shutdown import raise_if_requested

    n0 = sup.last_beat()[0]
    raise_if_requested(where="chunk")       # no shutdown pending: no raise
    assert sup.last_beat()[0] == n0 + 1
    assert sup.last_beat()[2] == "chunk"


# ---------------------------------------------------------------------------
# the watchdog ladder
# ---------------------------------------------------------------------------


def test_watchdog_detects_stall_and_requests_graceful_shutdown():
    flight.clear()
    with sup.supervision(stall_timeout_s=0.08, poll_s=0.02,
                         grace_s=60.0):
        sup.beat("chunk")               # first boundary: steady state begins
        deadline = time.monotonic() + 3.0
        while not shutdown_requested() and time.monotonic() < deadline:
            time.sleep(0.02)            # NOT beating: this is the stall
        assert shutdown_requested(), "watchdog never noticed the stall"
    events = [e for e in flight.snapshot()
              if e.get("name") == "supervise.stall_detected"]
    assert events, "stall detection left no flight evidence"
    attrs = events[-1]["attrs"]
    assert attrs["age_s"] >= 0.08
    assert attrs["where"] == "chunk"    # the last boundary crossed


def test_watchdog_startup_grace_covers_the_cold_start():
    """Before the first boundary beat of the scope, only the (longer)
    startup grace applies — a cold start (import + compile) longer than
    the steady-state stall timeout must not be preempted."""
    with sup.supervision(stall_timeout_s=0.05, poll_s=0.02,
                         startup_grace_s=5.0, grace_s=60.0):
        time.sleep(0.3)                 # "compiling": 6x the stall timeout
        assert not shutdown_requested(), \
            "watchdog preempted a legitimate cold start"
        sup.beat("chunk")               # steady state: the short clock arms
        deadline = time.monotonic() + 3.0
        while not shutdown_requested() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert shutdown_requested()


def test_watchdog_does_not_fire_while_beating():
    with sup.supervision(stall_timeout_s=0.2, poll_s=0.02, grace_s=60.0):
        t_end = time.monotonic() + 0.6
        while time.monotonic() < t_end:
            sup.beat("chunk")
            time.sleep(0.03)
        assert not shutdown_requested(), \
            "watchdog fired on a run that was heartbeating"


def test_watchdog_hard_aborts_wedged_run(tmp_path, monkeypatch):
    """Escalation rung 2: the graceful request is ignored (no beats arrive)
    for a whole grace window — the injected abort hook fires and the flight
    post-mortem names the stalled boundary."""
    monkeypatch.chdir(tmp_path)
    flight.clear()
    aborted = []
    sup.beat("rep")                     # the boundary the stall will name
    # startup grace shrunk: this scenario IS the wedged-before-boundary
    # class (device init hang) the grace exists to give time to
    wd = sup.Watchdog(stall_timeout_s=0.05, grace_s=0.1, poll_s=0.02,
                      startup_grace_s=0.05,
                      abort=lambda: aborted.append(True)).start()
    try:
        deadline = time.monotonic() + 3.0
        while not aborted and time.monotonic() < deadline:
            time.sleep(0.02)
    finally:
        wd.stop()
    assert aborted, "watchdog never hard-aborted the wedged run"
    assert shutdown_requested()         # rung 1 fired first
    pm = tmp_path / "obs_postmortem.jsonl"
    assert pm.exists(), "hard abort left no flight post-mortem"
    from graphdyn.obs.recorder import read_ledger

    events, torn = read_ledger(str(pm))
    assert torn == 0
    crash = [e for e in events if e.get("name") == "obs.crash"]
    assert crash and "stalled past rep" in crash[-1]["attrs"]["site"]


def test_watchdog_deadline_requests_graceful_shutdown():
    flight.clear()
    with sup.supervision(deadline_s=0.06, poll_s=0.02):
        deadline = time.monotonic() + 3.0
        while not shutdown_requested() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert shutdown_requested(), "deadline never fired"
    events = [e for e in flight.snapshot()
              if e.get("name") == "supervise.deadline"]
    assert events and events[-1]["attrs"]["deadline_s"] == 0.06


def test_supervision_without_knobs_is_a_noop():
    with sup.supervision(None, None) as wd:
        assert wd is None               # no thread, no beat, no cost


def test_env_float_is_lenient(monkeypatch):
    monkeypatch.setenv("GRAPHDYN_STALL_TIMEOUT", "garbage")
    assert sup.env_float("GRAPHDYN_STALL_TIMEOUT") is None
    monkeypatch.setenv("GRAPHDYN_STALL_TIMEOUT", "2.5")
    assert sup.env_float("GRAPHDYN_STALL_TIMEOUT") == 2.5
    monkeypatch.setenv("GRAPHDYN_STALL_TIMEOUT", "-1")
    assert sup.env_float("GRAPHDYN_STALL_TIMEOUT") is None


# ---------------------------------------------------------------------------
# the `stall` fault action
# ---------------------------------------------------------------------------


def test_stall_fault_sleeps_then_continues():
    spec = _faults.FaultSpec("rep.boundary", "stall", secs=0.12)
    with _faults.FaultPlan([spec]):
        t0 = time.monotonic()
        _faults.maybe_fail("rep.boundary", key="rep=0")   # must NOT raise
        assert time.monotonic() - t0 >= 0.12
        # side effect consumed: the next hit is past the window, no sleep
        t0 = time.monotonic()
        _faults.maybe_fail("rep.boundary", key="rep=1")
        assert time.monotonic() - t0 < 0.1


def test_stall_fault_is_sideeffect_only_at_transform_sites():
    spec = _faults.FaultSpec("checkpoint.read", "stall", secs=0.05)
    with _faults.FaultPlan([spec]):
        t0 = time.monotonic()
        out = _faults.transform_spec("checkpoint.read", "truncate", key="ck")
        assert out is None              # never misread as a transform
        assert time.monotonic() - t0 >= 0.05


# ---------------------------------------------------------------------------
# the supervise() restart loop (scripted runners)
# ---------------------------------------------------------------------------


def _scripted(rcs, site=None):
    """A runner returning the scripted exit codes; crash codes drop a
    minimal parseable post-mortem naming ``site`` in the episode cwd."""
    calls = []

    def run(args, cwd):
        os.makedirs(cwd, exist_ok=True)
        i = len(calls)
        calls.append(list(args))
        rc = rcs[min(i, len(rcs) - 1)]
        if rc not in (0, 75, 130) and site is not None:
            with open(os.path.join(cwd, "obs_postmortem.jsonl"), "w") as f:
                f.write(json.dumps({"ev": "manifest", "t": 0.0,
                                    "run": {"postmortem": True}}) + "\n")
                f.write(json.dumps({"ev": "counter", "t": 0.1,
                                    "name": "obs.crash", "inc": 1,
                                    "attrs": {"site": site}}) + "\n")
        return rc

    return run, calls


def _policy(quarantine_after=3, max_crashes=10):
    return sup.RestartPolicy(
        quarantine_after=quarantine_after, max_crashes=max_crashes,
        max_episodes=50,
        backoff=RetryPolicy(tries=8, base_delay_s=0.01, max_delay_s=0.05,
                            jitter=True),
    )


def test_supervise_preempt_resumes_and_finishes(tmp_path):
    runner, calls = _scripted([75, 75, 0])
    report = sup.supervise(["sa", "--n", "10"], workdir=str(tmp_path),
                           policy=_policy(), runner=runner,
                           journal_dir=str(tmp_path), sleep=lambda s: None)
    assert report["exit"] == 0 and len(calls) == 3
    assert [e["rc"] for e in report["episodes"]] == [75, 75, 0]
    events, problems = validate_journal(str(tmp_path / JOURNAL_NAME))
    assert problems == []
    restarts = [e for e in events if e.get("op") == "supervise.restart"]
    assert len(restarts) == 2
    assert all(r["kind"] == "preempt" for r in restarts)
    assert any(e.get("op") == "supervise.start" for e in events)


def test_supervise_bounds_consecutive_preemption_loops(tmp_path):
    """A deadline/stall-timeout shorter than the run's cold start would
    spin forever on exit-75 restarts: bounded auto-restart applies to
    preemptions too — the supervisor hands the 75 back to the scheduler
    after max_preempts consecutive ones."""
    runner, calls = _scripted([75])     # preempts every episode
    policy = _policy()
    policy.max_preempts = 4
    report = sup.supervise(["sa"], workdir=str(tmp_path), policy=policy,
                           runner=runner, journal_dir=str(tmp_path),
                           sleep=lambda s: None)
    assert report["exit"] == 75
    assert report["reason"] == "preemption budget exhausted"
    assert len(calls) == 4


def test_supervise_stops_on_abort(tmp_path):
    runner, calls = _scripted([130])
    report = sup.supervise(["sa"], workdir=str(tmp_path), policy=_policy(),
                           runner=runner, journal_dir=str(tmp_path))
    assert report["exit"] == 130 and len(calls) == 1
    assert not report["quarantined"]


def test_supervise_stops_immediately_on_usage_error(tmp_path):
    """argparse exit 2 is a deterministic config error: restarting it N
    times before quarantining would burn the whole crash budget proving
    what the first exit already said."""
    runner, calls = _scripted([2])
    report = sup.supervise(["sa", "--no-such-flag"], workdir=str(tmp_path),
                           policy=_policy(), runner=runner,
                           journal_dir=str(tmp_path), sleep=lambda s: None)
    assert report["exit"] == 2 and report["reason"] == "usage error"
    assert len(calls) == 1              # never restarted


def test_supervise_quarantines_same_site_crash_loop(tmp_path):
    runner, calls = _scripted([1], site="solver.py:42 in explode")
    slept = []
    report = sup.supervise(["sa"], workdir=str(tmp_path),
                           policy=_policy(quarantine_after=3),
                           runner=runner, journal_dir=str(tmp_path),
                           sleep=slept.append)
    assert report["exit"] == sup.EX_QUARANTINE
    assert report["quarantined"] and report["site"] == "solver.py:42 in explode"
    # exactly N episodes — never an N+1-th restart — and N-1 backoffs
    assert len(calls) == 3 and len(slept) == 2
    assert all(s > 0 for s in slept)
    bundle = report["bundle"]
    assert os.path.exists(bundle)
    with open(bundle) as f:
        doc = json.load(f)
    assert doc["site"] == "solver.py:42 in explode" and doc["crashes"] == 3
    assert len(doc["postmortems"]) == 3
    assert all(os.path.exists(p) for p in doc["postmortems"])
    events, problems = validate_journal(str(tmp_path / JOURNAL_NAME))
    assert problems == []
    q = [e for e in events if e.get("op") == "supervise.quarantine"]
    assert len(q) == 1 and q[0]["site"] == doc["site"] and q[0]["crashes"] == 3


def test_supervise_backoff_is_deterministic_per_site(tmp_path):
    """The PR-9 seeded full-jitter contract: the same crash site draws the
    same backoff schedule on every supervisor run (tests can pin it), while
    a different site draws a de-correlated one."""
    def run_once(d, site):
        runner, _ = _scripted([1], site=site)
        slept = []
        sup.supervise(["sa"], workdir=str(d), policy=_policy(),
                      runner=runner, journal_dir=str(d), sleep=slept.append)
        return slept

    a1 = run_once(tmp_path / "a1", "site.A")
    a2 = run_once(tmp_path / "a2", "site.A")
    b = run_once(tmp_path / "b", "site.B")
    assert a1 == a2
    assert a1 != b


def test_supervise_site_change_resets_streak_until_crash_budget(tmp_path):
    """Crashes alternating between two sites never trip the same-site
    quarantine; the TOTAL crash budget stops the loop instead."""
    sites = ["site.A", "site.B"]
    calls = []

    def runner(args, cwd):
        os.makedirs(cwd, exist_ok=True)
        i = len(calls)
        calls.append(1)
        with open(os.path.join(cwd, "obs_postmortem.jsonl"), "w") as f:
            f.write(json.dumps({"ev": "manifest", "t": 0.0, "run": {}})
                    + "\n")
            f.write(json.dumps({"ev": "counter", "t": 0.1,
                                "name": "obs.crash", "inc": 1,
                                "attrs": {"site": sites[i % 2]}}) + "\n")
        return 1

    report = sup.supervise(["sa"], workdir=str(tmp_path),
                           policy=_policy(quarantine_after=3, max_crashes=5),
                           runner=runner, journal_dir=str(tmp_path),
                           sleep=lambda s: None)
    assert not report["quarantined"]
    assert report["reason"] == "crash budget exhausted"
    assert len(calls) == 5


def test_supervise_crash_without_postmortem_keys_on_exit_code(tmp_path):
    runner, _ = _scripted([7])          # no post-mortem written
    report = sup.supervise(["sa"], workdir=str(tmp_path),
                           policy=_policy(quarantine_after=2),
                           runner=runner, journal_dir=str(tmp_path),
                           sleep=lambda s: None)
    assert report["exit"] == sup.EX_QUARANTINE
    assert report["site"] == "exit:7"


def test_supervise_forwards_watchdog_flags_to_child(tmp_path):
    runner, calls = _scripted([0])
    sup.supervise(["sa", "--n", "10"], workdir=str(tmp_path),
                  policy=_policy(), runner=runner,
                  stall_timeout_s=5.0, deadline_s=9.0,
                  journal_dir=str(tmp_path))
    assert calls[0] == ["--stall-timeout", "5.0", "--deadline", "9.0",
                        "sa", "--n", "10"]


def test_supervise_absolutizes_relative_paths(tmp_path, monkeypatch):
    """Episodes run in per-episode cwds, so a relative --checkpoint/--out
    would resolve somewhere different every episode — the preempted
    episode's snapshot invisible to the restarted one. supervise() anchors
    every path-valued child flag at its own cwd up front."""
    monkeypatch.chdir(tmp_path)
    runner, calls = _scripted([0])
    report = sup.supervise(
        ["--obs-ledger=led.jsonl", "sa", "--checkpoint", "ck/run",
         "--out", "res.npz", "--n", "10"],
        workdir=str(tmp_path), policy=_policy(), runner=runner)
    a = calls[0]
    assert a[a.index("--checkpoint") + 1] == str(tmp_path / "ck" / "run")
    assert a[a.index("--out") + 1] == str(tmp_path / "res.npz")
    assert f"--obs-ledger={tmp_path / 'led.jsonl'}" in a
    # the journal follows the absolutized checkpoint directory
    assert report["journal"] == str(tmp_path / "ck" / JOURNAL_NAME)


def test_checkpoint_dir_parsing():
    assert sup._checkpoint_dir(["sa", "--checkpoint", "/a/b/ck"]) == "/a/b"
    assert sup._checkpoint_dir(["sa", "--checkpoint=/a/b/ck"]) == "/a/b"
    assert sup._checkpoint_dir(["sa", "--checkpoint", "ck"]) == "."
    assert sup._checkpoint_dir(["sa", "--n", "10"]) is None


# ---------------------------------------------------------------------------
# journal schema
# ---------------------------------------------------------------------------


def test_validate_journal_rejects_incomplete_supervise_events(tmp_path):
    from graphdyn.resilience.store import _reset_journal_state, journal_event

    _reset_journal_state()
    jpath = str(tmp_path / JOURNAL_NAME)
    journal_event(jpath, "supervise.start", argv=["sa"])
    journal_event(jpath, "supervise.restart", episode=0, rc=75,
                  kind="preempt")
    journal_event(jpath, "supervise.quarantine", site="x", crashes=3)
    _, problems = validate_journal(jpath)
    assert problems == []
    journal_event(jpath, "supervise.restart", rc=1)       # missing fields
    _, problems = validate_journal(jpath)
    assert any("supervise.restart" in p and "episode" in p
               for p in problems)
    assert any("supervise.restart" in p and "kind" in p for p in problems)


# ---------------------------------------------------------------------------
# CLI surfaces
# ---------------------------------------------------------------------------


def test_supervisor_main_parses_flags_and_command(tmp_path, monkeypatch,
                                                  capsys):
    seen = {}

    def fake_supervise(cmd, **kw):
        seen["cmd"] = cmd
        seen.update(kw)
        return {"exit": 0, "reason": "completed", "episodes": [],
                "quarantined": False, "journal": "j"}

    monkeypatch.setattr(sup, "supervise", fake_supervise)
    rc = sup.main(["--stall-timeout", "5", "--workdir", str(tmp_path),
                   "--format", "json", "--", "sa", "--n", "10"])
    assert rc == 0
    assert seen["cmd"] == ["sa", "--n", "10"]
    assert seen["stall_timeout_s"] == 5.0
    assert seen["policy"].quarantine_after == 3
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1 and json.loads(out[0])["exit"] == 0


def test_supervisor_main_requires_a_command():
    with pytest.raises(SystemExit):
        sup.main(["--stall-timeout", "5"])


def test_cli_run_supervised_delegates(monkeypatch):
    from graphdyn import cli

    seen = {}
    monkeypatch.setattr(sup, "main",
                        lambda cmd: seen.setdefault("cmd", cmd) and 0 or 0)
    rc = cli.main(["run-supervised", "--stall-timeout", "5", "--",
                   "sa", "--n", "10"])
    assert rc == 0
    assert seen["cmd"] == ["--stall-timeout", "5", "--", "sa", "--n", "10"]


def test_cli_run_supervised_forwards_presubcommand_flags(monkeypatch):
    """Top-level flags placed BEFORE the run-supervised subcommand reach
    the supervisor (watchdog knobs) and the child (store/obs knobs) — a
    silently dropped --stall-timeout would run with no watchdog at all."""
    from graphdyn import cli

    seen = {}
    real_main = sup.main
    monkeypatch.setattr(sup, "main",
                        lambda cmd: seen.setdefault("cmd", cmd) and 0 or 0)
    rc = cli.main(["--stall-timeout", "300", "--ckpt-keep", "3",
                   "run-supervised", "--", "sa", "--n", "10"])
    assert rc == 0
    cmd = seen["cmd"]
    assert cmd[:2] == ["--stall-timeout", "300.0"]
    sep = cmd.index("--")
    assert cmd[sep + 1:] == ["--ckpt-keep", "3", "sa", "--n", "10"]
    # and the supervisor's own parser accepts exactly this handoff shape
    captured = {}

    def fake_supervise(child, **kw):
        captured["child"] = child
        captured.update(kw)
        return {"exit": 0, "reason": "completed", "episodes": [],
                "quarantined": False, "journal": "j"}

    monkeypatch.setattr(sup, "supervise", fake_supervise)
    assert real_main(cmd) == 0
    assert captured["stall_timeout_s"] == 300.0
    assert captured["child"] == ["--ckpt-keep", "3", "sa", "--n", "10"]
