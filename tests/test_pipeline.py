"""graphdyn.pipeline: batched multi-graph ensembles + prefetch overlap.

The contract under test (ARCHITECTURE.md "Ensemble pipeline"):

1. the grouped drivers are ELEMENT-WISE IDENTICAL to the serial drivers —
   same per-repetition ``mag_reached``/``num_steps``/``conf``/``graphs`` —
   for several group sizes including 1 and non-divisors of the repetition
   count (pad rows must be inert);
2. prefetch depth cannot change results (builds are pure functions of
   ``seed + k``);
3. the PR-2 resilience contract survives grouping: ``rep.boundary``
   preempt/signal → snapshot → resume → results equal the uninterrupted
   run, with snapshots interchangeable across group sizes;
4. the stacked layout shards over a device mesh bit-identically.
"""

import os

import numpy as np
import pytest

from graphdyn.config import DynamicsConfig, HPRConfig, SAConfig
from graphdyn.models.hpr import hpr_ensemble
from graphdyn.models.sa import sa_ensemble
from graphdyn.pipeline.groups import group_ranges
from graphdyn.pipeline.prefetch import HostPrefetcher
from graphdyn.resilience import (
    FaultPlan, FaultSpec, InjectedPreemption, ShutdownRequested,
    graceful_shutdown,
)
from graphdyn.utils.io import Checkpoint

DYN11 = DynamicsConfig(p=1, c=1)
SA_CFG = SAConfig(dynamics=DYN11)
SA_KW = dict(n_stat=5, seed=0, max_steps=20_000)


def _assert_ensembles_equal(a, b):
    for f in a._fields:
        if f == "time":        # wall-clock is not a deterministic observable
            continue
        np.testing.assert_array_equal(getattr(a, f), getattr(b, f), err_msg=f)


# ---------------------------------------------------------------------------
# 1. element-wise parity, grouped vs serial
# ---------------------------------------------------------------------------


def test_sa_grouped_matches_serial_elementwise():
    """Group sizes 1 (vmapped singleton), 2 (several groups), and 4 (a
    non-divisor of n_stat=5 — the tail group runs padded) all reproduce the
    serial driver exactly, per repetition."""
    base = sa_ensemble(30, 3, SA_CFG, group_size=0, **SA_KW)
    for gs in (1, 2, 4):
        res = sa_ensemble(30, 3, SA_CFG, group_size=gs, **SA_KW)
        _assert_ensembles_equal(base, res)


def test_hpr_grouped_matches_serial_elementwise():
    cfg = HPRConfig(dynamics=DYN11, max_sweeps=2000)
    kw = dict(n_rep=3, seed=1)
    base = hpr_ensemble(30, 3, cfg, group_size=0, **kw)
    for gs in (1, 2):          # 2 is a non-divisor of n_rep=3 (padded tail)
        res = hpr_ensemble(30, 3, cfg, group_size=gs, **kw)
        _assert_ensembles_equal(base, res)
        assert np.all(res.time > 0)


def test_hpr_grouped_matches_serial_long_chains():
    """Regression anchor for the parity design: n=60, d=4, seed=5 drives an
    800+-sweep chain whose decisions flip under ulp-level float-schedule
    differences — the case that exposed fused-loop-vs-restatement
    divergence and forced hpr_solve onto the shared group program. Serial
    (a loop of hpr_solve) and grouped must stay element-wise identical."""
    cfg = HPRConfig(dynamics=DYN11, max_sweeps=1000)
    kw = dict(n_rep=3, seed=5)
    base = hpr_ensemble(60, 4, cfg, group_size=0, **kw)
    res = hpr_ensemble(60, 4, cfg, group_size=2, **kw)
    _assert_ensembles_equal(base, res)


def test_sa_grouped_rejected_off_jax_backends():
    """An explicit group size with the numpy oracle (or lightcone mode)
    must fail loudly, never silently fall back."""
    with pytest.raises(ValueError, match="group_size"):
        sa_ensemble(30, 3, SA_CFG, group_size=2, backend="cpu", **SA_KW)
    # the auto default quietly picks the serial loop for the oracle
    res = sa_ensemble(30, 3, SA_CFG, backend="cpu", n_stat=2, seed=0,
                      max_steps=20_000)
    assert res.conf.shape == (2, 30)


# ---------------------------------------------------------------------------
# 2. prefetch determinism
# ---------------------------------------------------------------------------


def test_prefetch_depth_does_not_change_results():
    r0 = sa_ensemble(30, 3, SA_CFG, group_size=2, prefetch=0, **SA_KW)
    r4 = sa_ensemble(30, 3, SA_CFG, group_size=2, prefetch=4, **SA_KW)
    _assert_ensembles_equal(r0, r4)


def test_prefetcher_unit():
    built = []

    def build(k):
        built.append(k)
        return k * k

    with HostPrefetcher(build, range(5), depth=2) as pf:
        assert [pf.get(k) for k in range(5)] == [0, 1, 4, 9, 16]
    assert built == list(range(5))
    # depth=0 is synchronous — no thread, same values
    with HostPrefetcher(build, range(3), depth=0) as pf:
        assert [pf.get(k) for k in range(3)] == [0, 1, 4]
    # out-of-order consumption is a programming error, not a silent desync
    with HostPrefetcher(build, range(3), depth=1) as pf:
        with pytest.raises(ValueError, match="out of order"):
            pf.get(1)


def test_prefetcher_build_failure_surfaces_on_consumer():
    def build(k):
        if k == 2:
            raise RuntimeError("boom at 2")
        return k

    with HostPrefetcher(build, range(4), depth=3) as pf:
        assert pf.get(0) == 0
        assert pf.get(1) == 1
        with pytest.raises(RuntimeError, match="repetition 2"):
            pf.get(2)


def test_prefetcher_hung_worker_is_reported(caplog):
    """A build stuck past the stop flag (syscall, native code) makes
    close()'s join expire: the wedged daemon thread must be REPORTED — a
    warning plus the pipeline.prefetch.hung counter in the flight ring —
    not silently abandoned, so a watchdog post-mortem can name the stalled
    prefetcher (ARCHITECTURE.md 'Supervised execution')."""
    import logging
    import threading

    from graphdyn.obs import flight

    release = threading.Event()

    def build(k):
        release.wait(20)                # ignores close()'s stop flag
        return k

    pf = HostPrefetcher(build, [0, 1], depth=1)
    try:
        flight.clear()
        with caplog.at_level(logging.WARNING, logger="graphdyn.pipeline"):
            pf.close(timeout_s=0.2)    # the worker cannot exit in time
        assert any("HUNG" in r.message for r in caplog.records)
        hung = [e for e in flight.snapshot()
                if e.get("name") == "pipeline.prefetch.hung"]
        assert hung and hung[-1]["attrs"]["timeout_s"] == 0.2
    finally:
        release.set()                   # let the daemon thread die
    # a healthy close stays silent (no counter)
    flight.clear()
    with HostPrefetcher(lambda k: k, range(3), depth=1) as pf2:
        assert pf2.get(0) == 0
    assert not [e for e in flight.snapshot()
                if e.get("name") == "pipeline.prefetch.hung"]


def test_group_ranges_partition():
    assert list(group_ranges(0, 5, 2)) == [[0, 1], [2, 3], [4]]
    assert list(group_ranges(3, 5, 8)) == [[3, 4]]
    assert list(group_ranges(5, 5, 2)) == []
    with pytest.raises(ValueError):
        list(group_ranges(0, 5, 0))


# ---------------------------------------------------------------------------
# 3. resilience contract under grouping
# ---------------------------------------------------------------------------


def test_sa_grouped_rep_preemption_resume_parity(tmp_path):
    """A hard preemption at the rep-1 boundary (inside a group's boundary
    sweep) resumes to results identical to the uninterrupted grouped run —
    and to the serial run, by the parity above."""
    ck = str(tmp_path / "ck")
    base = sa_ensemble(30, 3, SA_CFG, group_size=2, **SA_KW)
    with FaultPlan([FaultSpec("rep.boundary", "preempt", at=2)]):
        with pytest.raises(InjectedPreemption):
            sa_ensemble(30, 3, SA_CFG, group_size=2, checkpoint_path=ck,
                        checkpoint_interval_s=0.0, **SA_KW)
    res = sa_ensemble(30, 3, SA_CFG, group_size=2, checkpoint_path=ck,
                      checkpoint_interval_s=0.0, **SA_KW)
    _assert_ensembles_equal(base, res)
    assert not os.path.exists(ck + ".npz")


def test_sa_grouped_resume_across_group_sizes(tmp_path):
    """Snapshots are interchangeable between group sizes (and with the
    serial path): per-repetition results depend only on seed + k, so a
    resume may regroup freely."""
    ck = str(tmp_path / "ck")
    base = sa_ensemble(30, 3, SA_CFG, group_size=0, **SA_KW)
    with FaultPlan([FaultSpec("rep.boundary", "preempt", at=3)]):
        with pytest.raises(InjectedPreemption):
            sa_ensemble(30, 3, SA_CFG, group_size=3, checkpoint_path=ck,
                        checkpoint_interval_s=0.0, **SA_KW)
    res = sa_ensemble(30, 3, SA_CFG, group_size=0, checkpoint_path=ck,
                      checkpoint_interval_s=0.0, **SA_KW)
    _assert_ensembles_equal(base, res)


def test_sa_grouped_shutdown_snapshots_prefix(tmp_path):
    """The graceful-shutdown protocol at a group boundary: the 'signal'
    action (SIGTERM semantics) propagates ShutdownRequested with the
    completed-rep prefix snapshotted; the rerun completes bit-exactly."""
    ck = str(tmp_path / "ck")
    base = sa_ensemble(30, 3, SA_CFG, group_size=2, **SA_KW)
    with graceful_shutdown():
        with FaultPlan([FaultSpec("rep.boundary", "signal", at=1)]):
            with pytest.raises(ShutdownRequested):
                sa_ensemble(30, 3, SA_CFG, group_size=2, checkpoint_path=ck,
                            checkpoint_interval_s=1e9, **SA_KW)
    arrays, meta = Checkpoint(ck).load()
    assert meta["next_rep"] == 1
    res = sa_ensemble(30, 3, SA_CFG, group_size=2, checkpoint_path=ck,
                      checkpoint_interval_s=0.0, **SA_KW)
    _assert_ensembles_equal(base, res)
    assert not os.path.exists(ck + ".npz")


def test_grouped_resume_cleans_stale_serial_chain_files(tmp_path):
    """A SERIAL-path run preempted mid-repetition leaves its in-flight
    chain snapshot at <path>_chain<k>; a grouped-path resume recomputes
    that repetition from scratch and must REMOVE the stale file — a later
    serial run reusing the checkpoint path would otherwise hit the chain
    fingerprint check and refuse to resume, wedging mid-ensemble."""
    ck = str(tmp_path / "ck")
    base = sa_ensemble(30, 3, SA_CFG, group_size=0, **SA_KW)
    # manufacture the serial driver's preemption leftovers: a prefix
    # snapshot at rep 1 plus rep 1's in-flight chain file
    run_id = {"seed": SA_KW["seed"], "n_stat": SA_KW["n_stat"], "n": 30,
              "d": 3, "max_steps": SA_KW["max_steps"],
              "graph_method": "pairing", "config": repr(SA_CFG),
              "backend": "jax_tpu"}
    Checkpoint(ck).save(
        {"mag_reached": base.mag_reached, "num_steps": base.num_steps,
         "conf": base.conf, "m_final": base.m_final},
        {**run_id, "next_rep": 1},
    )
    Checkpoint(ck + "_chain1").save(
        {"s": np.zeros((1, 30), np.int8)},
        {"kind": "sa_chain", "seed": 99, "fp": "stale-serial-snapshot"},
    )
    res = sa_ensemble(30, 3, SA_CFG, group_size=2, checkpoint_path=ck,
                      checkpoint_interval_s=0.0, **SA_KW)
    _assert_ensembles_equal(base, res)
    assert not os.path.exists(ck + "_chain1.npz")   # stale file removed
    assert not os.path.exists(ck + ".npz")


def test_hpr_grouped_rep_preemption_resume_parity(tmp_path):
    cfg = HPRConfig(dynamics=DYN11, max_sweeps=2000)
    kw = dict(n_rep=3, seed=1)
    ck = str(tmp_path / "ck")
    base = hpr_ensemble(30, 3, cfg, group_size=2, **kw)
    with FaultPlan([FaultSpec("rep.boundary", "preempt", at=2)]):
        with pytest.raises(InjectedPreemption):
            hpr_ensemble(30, 3, cfg, group_size=2, checkpoint_path=ck,
                         checkpoint_interval_s=0.0, **kw)
    res = hpr_ensemble(30, 3, cfg, group_size=2, checkpoint_path=ck,
                       checkpoint_interval_s=0.0, **kw)
    _assert_ensembles_equal(base, res)
    assert not os.path.exists(ck + ".npz")


def test_cli_grouped_sa_preemption_exits_75_and_resumes(tmp_path, capsys,
                                                        monkeypatch):
    """The PR-2 CLI contract under batching, end to end: a shutdown request
    at a group boundary of the GROUPED sa driver exits EX_TEMPFAIL (75)
    with a loadable prefix snapshot; rerunning the same command resumes,
    completes with exit 0, cleans the checkpoint up, and the persisted
    results are bit-exact vs an uninterrupted run."""
    import json

    from graphdyn.cli import main
    from graphdyn.utils.io import load_results_npz

    # a no-ledger preempt dumps the flight post-mortem into the workdir
    # (PR-8 contract, asserted in tests/test_obs_device.py) — keep it here
    monkeypatch.chdir(tmp_path)

    ck = str(tmp_path / "ck")
    out = str(tmp_path / "res.npz")
    base_out = str(tmp_path / "base.npz")
    common = [
        "sa", "--n", "30", "--d", "3", "--p", "1", "--c", "1",
        "--n-stat", "3", "--max-steps", "20000", "--seed", "0",
        "--group-size", "2", "--prefetch", "2",
    ]
    rc = main(common + ["--out", base_out])
    capsys.readouterr()
    assert rc == 0
    args = common + ["--checkpoint", ck, "--checkpoint-interval", "0",
                     "--out", out]
    with FaultPlan([FaultSpec("rep.boundary", "signal", at=1)]):
        rc = main(args)
    capsys.readouterr()
    assert rc == 75                              # preempted, requeue me
    loaded = Checkpoint(ck).load()
    assert loaded is not None and loaded[1]["next_rep"] >= 1
    rc2 = main(args)                             # requeue
    doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc2 == 0
    assert not os.path.exists(ck + ".npz")
    base, res = load_results_npz(base_out), load_results_npz(out)
    for key in base:
        np.testing.assert_array_equal(base[key], res[key], err_msg=key)
    assert doc["solver"] == "sa"


# ---------------------------------------------------------------------------
# 4. stacked layout over a device mesh
# ---------------------------------------------------------------------------


def test_sa_group_sharded_over_mesh_bit_identical():
    """The stacked [G, ...] layout shards over the group axis with no
    change in per-repetition results (repetitions are independent, so the
    partitioned program computes exactly the unsharded arithmetic)."""
    from graphdyn.models.sa import prepare_sa_inputs
    from graphdyn.parallel.mesh import device_pool, make_mesh
    from graphdyn.pipeline.sa_group import run_sa_group
    from graphdyn.graphs import random_regular_graph

    seeds = [7 + k for k in range(4)]
    graphs = [random_regular_graph(30, 3, seed=s) for s in seeds]
    preps = [
        prepare_sa_inputs(g, SA_CFG, n_replicas=1, seed=s, max_steps=20_000)
        for g, s in zip(graphs, seeds)
    ]
    base = run_sa_group(graphs, preps, seeds, SA_CFG, group_size=4)
    mesh = make_mesh((2,), ("group",), devices=device_pool(2))
    res = run_sa_group(graphs, preps, seeds, SA_CFG, group_size=4, mesh=mesh)
    np.testing.assert_array_equal(base.s, res.s)
    np.testing.assert_array_equal(base.num_steps, res.num_steps)
    np.testing.assert_array_equal(base.m_final, res.m_final)


# ---------------------------------------------------------------------------
# 5. persistent compile cache wiring
# ---------------------------------------------------------------------------


def test_compile_cache_opt_in(tmp_path, monkeypatch):
    """GRAPHDYN_COMPILE_CACHE wires jax_compilation_cache_dir and compiled
    programs land in it; unset leaves the config untouched. The live check
    runs in a subprocess — jax memoizes cache enablement at the process's
    first compile, so a long-lived suite process cannot flip it on."""
    import subprocess
    import sys

    from graphdyn.utils.platform import apply_compile_cache

    monkeypatch.delenv("GRAPHDYN_COMPILE_CACHE", raising=False)
    assert apply_compile_cache() is None

    cache = tmp_path / "xla-cache"
    code = (
        "import jax, jax.numpy as jnp\n"
        "from graphdyn.utils.platform import apply_compile_cache\n"
        "d = apply_compile_cache()\n"
        "assert jax.config.jax_compilation_cache_dir == d, d\n"
        "jax.jit(lambda x: (x * x).sum())("
        "jnp.arange(128, dtype=jnp.float32)).block_until_ready()\n"
    )
    env = {**os.environ, "GRAPHDYN_COMPILE_CACHE": str(cache),
           "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert any(cache.iterdir()), "no cache entries written"


# ---------------------------------------------------------------------------
# 6. cell-parallel entropy λ-ladders (pipeline.entropy_group)
# ---------------------------------------------------------------------------


def test_entropy_grouped_multicell_lambda_preemption_resume(tmp_path):
    """A hard preemption at a λ boundary of a multi-cell GROUP snapshots
    λ-granularly (every in-flight cell's last-boundary chi) and resumes —
    under a DIFFERENT group size — to results identical to the
    uninterrupted run."""
    import numpy as np

    from graphdyn.config import EntropyConfig
    from graphdyn.models.entropy import entropy_grid

    cfg = EntropyConfig(lmbd_max=0.2, lmbd_step=0.1, num_rep=2)
    deg = np.array([1.2, 1.6])
    ck = str(tmp_path / "ck")
    base = entropy_grid(40, deg, cfg, seed=3, group_size=4)
    with FaultPlan([FaultSpec("lambda.boundary", "preempt", at=5)]):
        with pytest.raises(InjectedPreemption):
            entropy_grid(40, deg, cfg, seed=3, group_size=4,
                         checkpoint_path=ck, checkpoint_interval_s=0.0)
    loaded = Checkpoint(ck).load()
    assert loaded is not None and "cells" in loaded[1]   # grouped format
    res = entropy_grid(40, deg, cfg, seed=3, group_size=2,
                       checkpoint_path=ck, checkpoint_interval_s=0.0)
    for f in base._fields:
        np.testing.assert_array_equal(getattr(base, f), getattr(res, f),
                                      err_msg=f)
    assert not os.path.exists(ck + ".npz")


def test_entropy_cell_group_sharded_over_mesh_bit_identical():
    """The stacked [G, …] cell layout shards over the cell axis
    (parallel.mesh.shard_stack) with no change in per-cell ladder results
    — cells are independent, so the partitioned program computes exactly
    the unsharded arithmetic."""
    import numpy as np

    from graphdyn.config import EntropyConfig
    from graphdyn.graphs import erdos_renyi_graph, remove_isolates
    from graphdyn.ops.bdcm import BDCMData
    from graphdyn.parallel.mesh import device_pool, make_mesh
    from graphdyn.pipeline.entropy_group import (
        EntropyCellExec, run_cell_ladder,
    )

    cfg = EntropyConfig(lmbd_max=0.2, lmbd_step=0.1)
    cells, chis = [], []
    for s in range(4):
        g = erdos_renyi_graph(40, (1.0 + 0.3 * s) / 39, seed=s)
        sub, n_iso = remove_isolates(g)
        data = BDCMData(sub, p=1, c=1, class_bucket=32)
        cells.append((data, g.n, n_iso))
        chis.append(np.asarray(data.init_messages(s)))
    lambdas = np.array([0.0, 0.1, 0.2])
    kw = dict(eps=cfg.eps, ent_floor=cfg.ent_floor)
    ex = EntropyCellExec(cells, cfg, group_size=4)
    base = run_cell_ladder(ex, chis, lambdas, **kw)
    mesh = make_mesh((2,), ("cell",), devices=device_pool(2))
    exm = EntropyCellExec(cells, cfg, group_size=4, mesh=mesh)
    res = run_cell_ladder(exm, chis, lambdas, **kw)
    for g in range(4):
        np.testing.assert_array_equal(base.ent1[g], res.ent1[g])
        np.testing.assert_array_equal(base.sweeps[g], res.sweeps[g])
        np.testing.assert_array_equal(base.chi[g], res.chi[g])
    np.testing.assert_array_equal(base.nonconverged, res.nonconverged)
    # indivisible group/mesh shapes are refused loudly
    with pytest.raises(ValueError, match="not divisible"):
        EntropyCellExec(cells[:3], cfg, group_size=3, mesh=mesh)
