"""Out-of-core streamed rollout tests (ISSUE 19): bit-parity against the
resident kernels across the rule × tie × graph-family × chunking matrix,
stream-plan construction and its refusals, live edge churn against a
piecewise resident oracle, preemption/resume with journal-alone churn
replay (a tampered past schedule must not matter), the SA
``layout='streamed'`` route, and the CLI ``stream`` subcommand."""

import json
import os

import numpy as np
import pytest

from graphdyn.config import DynamicsConfig, SAConfig
from graphdyn.graphs import from_edgelist, powerlaw_graph, random_regular_graph
from graphdyn.models.sa import sa_ensemble, simulated_annealing
from graphdyn.ops.bucketed import bucketed_rollout_global
from graphdyn.ops.packed import pack_spins, packed_rollout, unpack_spins
from graphdyn.ops.streamed import (
    ChurnBatch,
    build_stream_plan,
    chunk_device_bytes,
    plan_device_bytes,
    seeded_churn,
    streamed_rollout,
)
from graphdyn.resilience import (
    FaultPlan,
    FaultSpec,
    ShutdownRequested,
    graceful_shutdown,
)
from graphdyn.resilience.store import journal_path_for, validate_journal
from graphdyn.utils.io import Checkpoint


def _graph(kind, n, seed):
    if kind == "rrg":
        return random_regular_graph(n, 3, seed=seed)
    return powerlaw_graph(n, gamma=2.3, dmin=2, seed=seed)


def _sp0(n, R, seed):
    rng = np.random.default_rng(seed)
    s0 = (2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8)
    return pack_spins(s0)


# ---------------------------------------------------------------------------
# bit-parity vs the resident kernels
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["rrg", "powerlaw"])
@pytest.mark.parametrize("rule,tie", [
    ("majority", "stay"), ("majority", "change"),
    ("minority", "stay"), ("minority", "change"),
])
@pytest.mark.parametrize("K", [1, 3])
def test_streamed_matches_resident_kernels(kind, rule, tie, K):
    g = _graph(kind, 80, seed=4)
    sp = _sp0(g.n, 32, seed=11)
    got = streamed_rollout(g, sp, 3, rule=rule, tie=tie, n_chunks=K)
    ref = np.asarray(packed_rollout(g.nbr, g.deg, sp, 3, rule, tie))
    np.testing.assert_array_equal(got, ref)
    ref_b = np.asarray(bucketed_rollout_global(g, sp, 3, rule, tie))
    np.testing.assert_array_equal(got, ref_b)


def test_streamed_budget_mode_parity_and_modeled_peak():
    g = powerlaw_graph(256, gamma=2.3, dmin=2, seed=7)
    sp = _sp0(g.n, 64, seed=3)                    # W = 2
    W = sp.shape[1]
    resident = chunk_device_bytes(g.n, g.n, int(g.nbr.shape[1]), W)
    budget = resident // 3
    plan = build_stream_plan(g, W=W, device_budget_bytes=budget)
    assert plan.K >= 2                            # actually streaming
    # every node owned exactly once, and the double-buffer peak honors
    # the budget the plan was packed against
    owned = np.sort(np.concatenate([c.nodes for c in plan.chunks]))
    np.testing.assert_array_equal(owned, np.arange(g.n))
    np.testing.assert_array_equal(
        plan.chunk_of[plan.chunks[1].nodes], 1)
    assert plan_device_bytes(plan, W) <= budget
    got = streamed_rollout(g, sp, 4, plan=plan)
    ref = np.asarray(packed_rollout(g.nbr, g.deg, sp, 4))
    np.testing.assert_array_equal(got, ref)


def test_streamed_prefetch_depth_is_parity_neutral_and_stats_report():
    g = _graph("powerlaw", 160, seed=9)
    sp = _sp0(g.n, 32, seed=1)
    outs, stats = {}, {}
    for depth in (0, 2):
        stats[depth] = {}
        outs[depth] = streamed_rollout(
            g, sp, 4, n_chunks=4, prefetch_depth=depth,
            stats_out=stats[depth])
    np.testing.assert_array_equal(outs[0], outs[2])
    for depth in (0, 2):
        st = stats[depth]
        assert st["steps"] == 4 and st["chunks"] == 4
        assert st["h2d_bytes"] > 0 and st["d2h_bytes"] > 0
        assert 0.0 <= st["overlap_frac"] <= 1.0
        assert st["mutations"] == 0


def test_build_stream_plan_refusals():
    g = random_regular_graph(32, 3, seed=0)
    with pytest.raises(ValueError, match="exactly one"):
        build_stream_plan(g, W=1)
    with pytest.raises(ValueError, match="exactly one"):
        build_stream_plan(g, W=1, n_chunks=2, device_budget_bytes=10**6)
    with pytest.raises(ValueError, match="n_chunks"):
        build_stream_plan(g, W=1, n_chunks=0)
    with pytest.raises(ValueError, match="n_chunks"):
        build_stream_plan(g, W=1, n_chunks=g.n + 1)
    # infeasible budget names the offending node, not a generic overflow
    with pytest.raises(ValueError, match="cannot be streamed"):
        build_stream_plan(g, W=1, device_budget_bytes=64)


def test_streamed_rejects_mismatched_state():
    g = random_regular_graph(16, 3, seed=0)
    with pytest.raises(ValueError, match="uint32"):
        streamed_rollout(g, np.zeros((g.n + 1, 1), np.uint32), 1, n_chunks=2)


# ---------------------------------------------------------------------------
# live edge churn vs a piecewise resident oracle
# ---------------------------------------------------------------------------


def _churn_oracle(g, sp, steps, schedule, rule="majority", tie="stay"):
    """Independent reference: maintain the live adjacency as python sets
    (the same drops-then-adds idempotent filter semantics) and advance one
    resident ``packed_rollout`` step per synchronous step."""
    n = g.n
    sets = [set(g.nbr[i, : g.deg[i]].astype(int).tolist()) for i in range(n)]
    applied = 0
    sp = np.asarray(sp, np.uint32)
    seq = 0
    for t in range(steps):
        while seq < len(schedule) and schedule[seq].step <= t:
            b = schedule[seq]
            for u, v in np.asarray(b.drops, np.int64).reshape(-1, 2):
                u, v = int(u), int(v)
                if u == v or v not in sets[u]:
                    continue
                sets[u].discard(v)
                sets[v].discard(u)
                applied += 1
            for u, v in np.asarray(b.adds, np.int64).reshape(-1, 2):
                u, v = int(u), int(v)
                if u == v or v in sets[u]:
                    continue
                sets[u].add(v)
                sets[v].add(u)
                applied += 1
            seq += 1
        edges = np.asarray(
            [(u, v) for u in range(n) for v in sorted(sets[u]) if u < v],
            np.int64).reshape(-1, 2)
        g_t = from_edgelist(edges, n=n)
        sp = np.asarray(packed_rollout(g_t.nbr, g_t.deg, sp, 1, rule, tie))
    return sp, applied


def test_churn_matches_piecewise_resident_oracle():
    g = random_regular_graph(64, 3, seed=2)
    sp = _sp0(g.n, 32, seed=5)
    schedule = seeded_churn(g.n, 6, rate=8.0, seed=13)
    assert schedule                               # non-vacuous
    ref, applied = _churn_oracle(g, sp, 6, schedule)
    stats = {}
    got = streamed_rollout(g, sp, 6, n_chunks=3, churn=schedule,
                           stats_out=stats)
    np.testing.assert_array_equal(got, ref)
    assert stats["mutations"] == applied and applied > 0


def test_seeded_churn_is_pure_in_its_arguments():
    a = seeded_churn(50, 5, rate=4.0, seed=3)
    b = seeded_churn(50, 5, rate=4.0, seed=3)
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert x.step == y.step
        np.testing.assert_array_equal(x.adds, y.adds)
        np.testing.assert_array_equal(x.drops, y.drops)


# ---------------------------------------------------------------------------
# preemption / resume — the journal-alone replay contract
# ---------------------------------------------------------------------------


@pytest.mark.faultinject
def test_streamed_preempt_checkpoints_and_resumes_bit_exact(tmp_path):
    g = random_regular_graph(48, 3, seed=1)
    sp = _sp0(g.n, 32, seed=8)
    kw = dict(n_chunks=3, seed=0)
    base = streamed_rollout(g, sp, 8, **kw)
    ck = str(tmp_path / "ck")
    with graceful_shutdown():
        # the 'signal' action delivers a shutdown request exactly as the
        # SIGTERM handler would — deterministically, at step boundary 2
        with FaultPlan([FaultSpec("chunk.boundary", "signal", at=2)]):
            with pytest.raises(ShutdownRequested):
                streamed_rollout(g, sp, 8, **kw, checkpoint_path=ck,
                                 checkpoint_interval_s=1e9)
    arrays, meta = Checkpoint(ck).load()
    assert meta["kind"] == "streamed_rollout"
    assert int(np.asarray(arrays["t"])) == 2      # no older than one step
    res = streamed_rollout(g, sp, 8, **kw, checkpoint_path=ck,
                           checkpoint_interval_s=1e9)
    np.testing.assert_array_equal(base, res)
    assert not os.path.exists(ck + ".npz")        # done: checkpoint removed


@pytest.mark.faultinject
def test_streamed_resume_replays_churn_from_journal_alone(tmp_path):
    """A requeued run's past comes from the ``stream.churn`` journal, NOT
    the schedule argument: resuming with a tampered past schedule still
    completes bit-exact to the fault-free run (and the journal validates
    clean)."""
    g = random_regular_graph(64, 3, seed=6)
    sp = _sp0(g.n, 32, seed=2)
    steps = 8
    schedule = seeded_churn(g.n, steps, rate=10.0, seed=21)
    base = streamed_rollout(g, sp, steps, n_chunks=3, churn=schedule)

    ck = str(tmp_path / "ck")
    with graceful_shutdown():
        with FaultPlan([FaultSpec("chunk.boundary", "signal", at=3)]):
            with pytest.raises(ShutdownRequested):
                streamed_rollout(g, sp, steps, n_chunks=3, churn=schedule,
                                 checkpoint_path=ck,
                                 checkpoint_interval_s=1e9)
    arrays, _ = Checkpoint(ck).load()
    t0 = int(np.asarray(arrays["t"]))
    assert t0 == 3

    jpath = journal_path_for(ck)
    events, problems = validate_journal(jpath)
    assert problems == []
    churn_past = [ev for ev in events
                  if ev.get("op") == "stream.churn" and ev["step"] < t0]
    assert churn_past                             # journaled past exists

    # tamper every already-applied batch: same (step, count) so the seq
    # cursor aligns, completely different edges — the journal, not this
    # schedule, must drive the replayed past
    rng = np.random.default_rng(99)
    tampered = [
        ChurnBatch(step=b.step,
                   adds=rng.integers(0, g.n, size=b.adds.shape,
                                     dtype=np.int64),
                   drops=rng.integers(0, g.n, size=b.drops.shape,
                                      dtype=np.int64))
        if b.step < t0 else b
        for b in schedule
    ]
    res = streamed_rollout(g, sp, steps, n_chunks=3, churn=tampered,
                           checkpoint_path=ck, checkpoint_interval_s=1e9)
    np.testing.assert_array_equal(base, res)
    _, problems = validate_journal(jpath)
    assert problems == []


# ---------------------------------------------------------------------------
# overlap evidence — the A/B hiding claim lives in a slow test only
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_prefetch_hides_half_the_gather_time():
    """At shapes where the device step does real work, the depth-2
    prefetch lane must hide >= 50% of the host gather/upload time that the
    depth-0 leg exposes (the ISSUE-19 acceptance A/B)."""
    g = powerlaw_graph(65536, gamma=2.2, dmin=2, seed=0)
    sp = _sp0(g.n, 1024, seed=0)                  # W = 32
    stats0, stats2 = {}, {}
    streamed_rollout(g, sp, 3, n_chunks=16, prefetch_depth=0,
                     stats_out=stats0)
    streamed_rollout(g, sp, 3, n_chunks=16, prefetch_depth=2,
                     stats_out=stats2)
    assert stats0["build_s"] > 0
    assert stats2["overlap_frac"] >= 0.5, (
        f"prefetch hid only {stats2['overlap_frac']:.1%} of "
        f"{stats2['build_s']:.3f}s gather time (sync leg: "
        f"{stats0['build_s']:.3f}s)"
    )


# ---------------------------------------------------------------------------
# SA layout='streamed' — same chain law through the out-of-core engine
# ---------------------------------------------------------------------------


def _sa_setup(n=48, d=3, R=3, L=300, seed=5):
    g = random_regular_graph(n, d, seed=seed)
    rng = np.random.default_rng(seed + 1)
    s0 = (2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8)
    proposals = rng.integers(0, n, size=(R, L)).astype(np.int32)
    uniforms = rng.random(size=(R, L))
    return g, s0, proposals, uniforms


def test_sa_streamed_layout_bit_parity():
    cfg = SAConfig(dynamics=DynamicsConfig(p=2, c=1))
    g, s0, proposals, uniforms = _sa_setup()
    kw = dict(s0=s0, proposals=proposals, uniforms=uniforms)
    r_str = simulated_annealing(g, cfg, **kw, layout="streamed",
                                stream_chunks=3)
    r_pad = simulated_annealing(g, cfg, **kw, layout="padded")
    r_cpu = simulated_annealing(g, cfg, **kw, backend="cpu")
    for ref in (r_pad, r_cpu):
        np.testing.assert_array_equal(r_str.s, ref.s)
        np.testing.assert_array_equal(r_str.num_steps, ref.num_steps)
        np.testing.assert_array_equal(r_str.m_final, ref.m_final)


def test_sa_streamed_layout_refusals():
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    g = random_regular_graph(20, 3, seed=0)
    with pytest.raises(ValueError, match="out-of-core"):
        simulated_annealing(g, cfg, layout="streamed", backend="cpu")
    with pytest.raises(ValueError, match="checkpointed SA chains"):
        simulated_annealing(g, cfg, layout="streamed", checkpoint_path="/tmp/x")
    with pytest.raises(ValueError, match="rollout_mode='full'"):
        simulated_annealing(g, cfg, layout="streamed",
                            rollout_mode="lightcone")


def test_sa_ensemble_streamed_matches_padded_serial():
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    kw = dict(n_stat=2, seed=4, max_steps=40)     # sentinel-bounded chains
    r_str = sa_ensemble(32, 3, cfg, **kw, layout="streamed",
                        stream_chunks=3)
    r_pad = sa_ensemble(32, 3, cfg, **kw, layout="padded", group_size=0)
    np.testing.assert_array_equal(r_str.conf, r_pad.conf)
    np.testing.assert_array_equal(r_str.num_steps, r_pad.num_steps)
    np.testing.assert_array_equal(r_str.m_final, r_pad.m_final)
    np.testing.assert_array_equal(r_str.graphs, r_pad.graphs)
    with pytest.raises(ValueError, match="group_size"):
        sa_ensemble(32, 3, cfg, **kw, layout="streamed", group_size=2)


# ---------------------------------------------------------------------------
# CLI stream subcommand
# ---------------------------------------------------------------------------


def test_cli_stream_subcommand_runs_and_saves(tmp_path, capsys):
    from graphdyn.cli import main

    out = str(tmp_path / "res.npz")
    rc = main([
        "stream", "--n", "96", "--gamma", "2.5", "--steps", "4",
        "--replicas", "8", "--chunks", "3", "--churn-rate", "4.0",
        "--churn-seed", "1", "--seed", "0", "--out", out,
    ])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert payload["solver"] == "stream" and payload["chunks"] == 3
    assert payload["h2d_bytes"] > 0 and payload["mutations"] > 0
    assert -1.0 <= payload["m_end_mean"] <= 1.0
    with np.load(out) as z:
        assert z["conf"].shape == (8, 96)
        assert set(np.unique(z["conf"])) <= {-1, 1}
        assert z["m_end"].shape == (8,)
    # the CLI leg is itself engine-parity: rebuild its exact run and
    # compare against the resident kernel end state
    g = powerlaw_graph(96, gamma=2.5, dmin=2, seed=0)
    rng = np.random.default_rng(0)
    s0 = (2 * rng.integers(0, 2, size=(8, 96)) - 1).astype(np.int8)
    schedule = seeded_churn(96, 4, rate=4.0, seed=1)
    ref, _ = _churn_oracle(g, pack_spins(s0), 4, schedule)
    with np.load(out) as z:
        np.testing.assert_array_equal(z["conf"], unpack_spins(ref, 8))
