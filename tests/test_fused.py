"""One-kernel annealing (ISSUE 14 / ROADMAP item 7): the fused
LUT-popcount SA chain.

The contract: ONE chain law, three executions — the XLA twin, the Pallas
kernel (interpret mode on this container), and the numpy single-flip
oracle — all bit-identical. The counter RNG is pinned deterministic per
(seed, site, step) with committed golden values (process-restart
stability), independent across sites, and invariant under replica-count
growth (pair granularity). A fixed-budget run performs ZERO device→host
transfers between snapshot boundaries (transfer-guard enforced), and the
compiled chunk program is ONE while loop with a donated carry (graftcheck
pins it; asserted live here too)."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from graphdyn.config import DynamicsConfig, SAConfig
from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.ops.dynamics import rule_coefficients
from graphdyn.ops.pallas_anneal import (
    FUSED_VMEM_BUDGET,
    build_fused_tables,
    counter_uniforms,
    counter_uniforms_np,
    fused_chunk_xla,
    fused_kernel_supported,
    fused_vmem_bytes,
    resolve_fused_mode,
)
from graphdyn.search.fused import _assemble_fused, _run_plan, fused_anneal


def _cfg(rule="majority", tie="stay"):
    return SAConfig(dynamics=DynamicsConfig(p=1, c=1, rule=rule, tie=tie))


# ---------------------------------------------------------------------------
# counter RNG: determinism, independence, golden values, growth invariance
# ---------------------------------------------------------------------------


def test_counter_rng_device_host_bit_parity():
    u_d = np.asarray(counter_uniforms(np.uint32(7), np.uint32(3), 50, 64))
    u_h = counter_uniforms_np(7, 3, 50, 64)
    np.testing.assert_array_equal(u_d, u_h)
    assert u_h.dtype == np.float32
    assert (u_h >= 0).all() and (u_h < 1).all()


def test_counter_rng_golden_values():
    """Committed constants pin the stream across process restarts, jax
    upgrades and containers: the Threefry body is pure uint32 arithmetic,
    so these values can only change if the cipher or the (key, counter)
    layout changes — which would silently re-randomize every fused chain.
    (Derived once from counter_uniforms_np(seed=0, step=0/1, n=4, Rp=32).)"""
    u0 = counter_uniforms_np(0, 0, 4, 32)
    u1 = counter_uniforms_np(0, 1, 4, 32)
    golden = {
        (0, 0, 0): u0[0, 0], (0, 3, 31): u0[3, 31],
        (1, 0, 0): u1[0, 0], (1, 2, 17): u1[2, 17],
    }
    # regenerate-and-compare keeps this self-checking; the committed
    # digest below is the actual restart anchor
    import hashlib

    digest = hashlib.sha256(
        u0.tobytes() + u1.tobytes()
    ).hexdigest()[:16]
    assert digest == "1c9f5e3926cbffd2", (digest, golden)


def test_counter_rng_site_step_independence():
    u = counter_uniforms_np(1, 5, 64, 32)
    # distinct sites draw (near-)distinct values — 24-bit uniforms over
    # 2048 draws expect ~0.1 birthday collisions; a broken counter layout
    # (repeated keys/counters) collapses whole rows or columns instead
    assert len(np.unique(u)) >= u.size - 4
    assert len(np.unique(u[:, 0])) == u.shape[0]     # no repeated nodes
    assert len(np.unique(u[0, :])) == u.shape[1]     # no repeated replicas
    # distinct steps re-randomize every site
    v = counter_uniforms_np(1, 6, 64, 32)
    assert (u != v).mean() > 0.999
    # distinct seeds re-randomize every site
    w = counter_uniforms_np(2, 5, 64, 32)
    assert (u != w).mean() > 0.999


def test_counter_rng_replica_growth_invariance():
    """The replica pair rides the KEY, not the counter: widening the
    replica set appends pair columns without perturbing existing ones."""
    small = counter_uniforms_np(9, 11, 40, 32)
    big = counter_uniforms_np(9, 11, 40, 128)
    np.testing.assert_array_equal(small, big[:, :32])


# ---------------------------------------------------------------------------
# the chain law: single-flip Metropolis oracle (state-, ΔΣ-, accept-equal)
# ---------------------------------------------------------------------------


def _end_sum_np(nbr, s, R_coef, C_coef):
    """One synchronous step per replica, the reference integer form."""
    s_ext = np.concatenate(
        [s.astype(np.int64), np.zeros((s.shape[0], 1), np.int64)], axis=1
    )
    sums = s_ext[:, nbr].sum(axis=2)
    return (R_coef * np.sign(2 * sums + C_coef * s.astype(np.int64))
            ).sum(axis=1)


@pytest.mark.pallas_interpret
@pytest.mark.parametrize("rule,tie", [("majority", "stay"),
                                      ("minority", "change")])
@pytest.mark.parametrize("gname", ["rrg", "er"])
def test_fused_chunk_matches_single_flip_oracle(gname, rule, tie):
    """Two full fused sweeps equal the product of per-site single-flip
    Metropolis kernels computed by brute force (full end-state
    re-evaluation per flip) under the SAME counter-RNG uniforms —
    including the additive ``Σs_end``, the device-resident schedule
    advance (cap-before-multiply at class granularity) and the accept
    count. Asserted for BOTH executions: the XLA twin and the Pallas
    kernel in interpret mode. The ISSUE-14 oracle-exactness acceptance
    criterion."""
    from graphdyn.ops.pallas_anneal import fused_chunk_pallas

    g = (random_regular_graph(60, 3, seed=1) if gname == "rrg"
         else erdos_renyi_graph(50, 4.0 / 49, seed=2))
    cfg = _cfg(rule, tie)
    R, seed = 5, 3
    state, tables_dev, static, tables, _, W, Rp = _assemble_fused(
        g, cfg, n_replicas=R, seed=seed, m_target=1.0, betas=None,
        tables=None,
    )
    n, chi = g.n, tables.chi
    Rc, Cc = rule_coefficients(rule, tie)
    st = fused_chunk_xla(
        state, jnp.uint32(seed), *tables_dev,
        chunk_steps=2 * chi, stop_on_first=False, **static,
    )
    state_p = _assemble_fused(
        g, cfg, n_replicas=R, seed=seed, m_target=1.0, betas=None,
        tables=tables,
    )[0]
    st_p = fused_chunk_pallas(
        state_p, jnp.uint32(seed), *tables_dev,
        chunk_steps=2 * chi, stop_on_first=False, interpret=True, **static,
    )
    np.testing.assert_array_equal(np.asarray(st.sp_ext),
                                  np.asarray(st_p.sp_ext))
    np.testing.assert_array_equal(np.asarray(st.sum_end),
                                  np.asarray(st_p.sum_end))
    assert int(st.accepted) == int(st_p.accepted)
    # numpy replay: same s0 draw, same uniforms, brute-force ΔΣ per site
    rng = np.random.default_rng(seed)
    s = (2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8)
    nbr = np.asarray(g.nbr)
    a = np.full(Rp, np.float32(cfg.a0_frac * n), np.float32)
    b = np.full(Rp, np.float32(cfg.b0_frac * n), np.float32)
    acap = np.float32(cfg.a_cap_frac * n)
    bcap = np.float32(cfg.b_cap_frac * n)
    se = _end_sum_np(nbr, s, Rc, Cc)
    accepted = 0
    for step in range(2 * chi):
        c = step % chi
        u = counter_uniforms_np(seed, step, n, Rp)
        sites = np.where(tables.chrom.colors == c)[0]
        for r in range(R):
            for i in sites:
                s_flip = s[r:r + 1].copy()
                s_flip[0, i] = -s_flip[0, i]
                ds = _end_sum_np(nbr, s_flip, Rc, Cc)[0] - se[r]
                de = (np.float32(-2.0) * a[r] * np.float32(s[r, i])
                      - b[r] * np.float32(ds)) / np.float32(n)
                if u[i, r] < np.exp(-de):
                    s[r, i] = -s[r, i]
                    se[r] += ds
                    accepted += 1
        a = np.where(a < acap, a * tables.fac_a[c], a).astype(np.float32)
        b = np.where(b < bcap, b * tables.fac_b[c], b).astype(np.float32)
    from graphdyn.ops.packed import unpack_spins

    got_s = unpack_spins(np.asarray(st.sp_ext[:n]), R)
    np.testing.assert_array_equal(got_s, s)
    np.testing.assert_array_equal(np.asarray(st.sum_end)[:R], se)
    np.testing.assert_array_equal(np.asarray(st.a)[:R], a[:R])
    np.testing.assert_array_equal(np.asarray(st.b)[:R], b[:R])
    assert int(st.accepted) == accepted
    # the additivity claim itself: Σs_end recomputed from the final state
    np.testing.assert_array_equal(_end_sum_np(nbr, s, Rc, Cc), se)


# ---------------------------------------------------------------------------
# one chain, three executions: XLA twin == Pallas kernel (interpret)
# ---------------------------------------------------------------------------


@pytest.mark.pallas_interpret
@pytest.mark.parametrize("R", [8, 64])      # W=1 and W=2 packed layouts
def test_fused_pallas_interpret_bit_identical_to_xla(R):
    g = random_regular_graph(96, 3, seed=0)
    kw = dict(n_replicas=R, seed=4, m_target=0.9, max_sweeps=400)
    x = fused_anneal(g, _cfg(), kernel="xla", **kw)
    p = fused_anneal(g, _cfg(), kernel="pallas", **kw)
    assert x.kernel_used == "xla" and p.kernel_used == "pallas-interpret"
    np.testing.assert_array_equal(x.s, p.s)
    np.testing.assert_array_equal(x.steps_to_target, p.steps_to_target)
    np.testing.assert_array_equal(x.m_end, p.m_end)
    assert x.accepted == p.accepted
    assert x.device_steps == p.device_steps


@pytest.mark.pallas_interpret
def test_fused_pallas_interpret_ragged_er():
    g = erdos_renyi_graph(64, 4.0 / 63, seed=3)
    kw = dict(n_replicas=8, seed=1, m_target=0.8, max_sweeps=200)
    x = fused_anneal(g, _cfg(), kernel="xla", **kw)
    p = fused_anneal(g, _cfg(), kernel="pallas", **kw)
    np.testing.assert_array_equal(x.s, p.s)
    assert x.accepted == p.accepted


# ---------------------------------------------------------------------------
# chunking, reproducibility, freeze semantics, drive ladder
# ---------------------------------------------------------------------------


def test_fused_chunk_split_invariance_and_reproducible():
    """The RNG counter is the GLOBAL step index, so chunk boundaries are
    invisible to the chain: any chunk_sweeps slicing — and any rerun —
    produces the identical run."""
    g = random_regular_graph(128, 3, seed=0)
    kw = dict(n_replicas=8, seed=0, m_target=0.9, max_sweeps=500)
    a = fused_anneal(g, _cfg(), chunk_sweeps=256, **kw)
    for cs in (37, 500, 1):
        b = fused_anneal(g, _cfg(), chunk_sweeps=cs, **kw)
        np.testing.assert_array_equal(a.s, b.s)
        np.testing.assert_array_equal(a.steps_to_target, b.steps_to_target)
        assert a.accepted == b.accepted
    c = fused_anneal(g, _cfg(), chunk_sweeps=256, **kw)
    np.testing.assert_array_equal(a.s, c.s)


def test_fused_replica_growth_invariance():
    """Replicas 0..R−1 of a wider run are bit-identical (independent bit
    columns + pair-keyed streams), across a word-count change W=1→2."""
    g = random_regular_graph(96, 3, seed=0)
    kw = dict(seed=4, m_target=0.9, max_sweeps=400)
    small = fused_anneal(g, _cfg(), n_replicas=32, **kw)
    big = fused_anneal(g, _cfg(), n_replicas=64, **kw)
    np.testing.assert_array_equal(small.s, big.s[:32])
    np.testing.assert_array_equal(small.steps_to_target,
                                  big.steps_to_target[:32])


def test_fused_first_passage_freezes():
    g = random_regular_graph(96, 3, seed=1)
    kw = dict(n_replicas=8, seed=3, m_target=0.9)
    short = fused_anneal(g, _cfg(), max_sweeps=300, **kw)
    longer = fused_anneal(g, _cfg(), max_sweeps=600, **kw)
    hit = short.steps_to_target >= 0
    assert hit.any()
    np.testing.assert_array_equal(short.steps_to_target[hit],
                                  longer.steps_to_target[hit])
    np.testing.assert_array_equal(short.s[hit], longer.s[hit])


def test_fused_long_plan_falls_back_to_synced_loop():
    """A plan past the no-op-dispatch bound (the shared
    MAX_FIXED_PLAN_CHUNKS) keeps the sanctioned per-chunk stop test —
    early exit once every replica froze, instead of thousands of no-op
    dispatches — and the chain is unchanged (chunk-split invariance)."""
    g = random_regular_graph(96, 3, seed=0)
    kw = dict(n_replicas=8, seed=0, m_target=0.9)
    ref = fused_anneal(g, _cfg(), max_sweeps=5000, chunk_sweeps=256, **kw)
    # 5000 one-sweep chunks > 4096: the synced fallback path
    long = fused_anneal(g, _cfg(), max_sweeps=5000, chunk_sweeps=1, **kw)
    np.testing.assert_array_equal(ref.s, long.s)
    np.testing.assert_array_equal(ref.steps_to_target, long.steps_to_target)
    assert ref.accepted == long.accepted


def test_fused_stop_on_first_and_budget():
    g = random_regular_graph(64, 3, seed=0)
    r = fused_anneal(g, _cfg(), n_replicas=8, seed=9, m_target=1.0,
                     max_sweeps=100, chunk_sweeps=64)
    assert r.sweeps <= 100 and r.device_steps == r.sweeps * r.chi
    s = fused_anneal(g, _cfg(), n_replicas=8, seed=0, m_target=0.8,
                     max_sweeps=400, chunk_sweeps=4, stop_on_first=True)
    assert (s.steps_to_target >= 0).any()


def test_fused_drive_ladder_on_replica_axis():
    """betas scale each replica's (b0, b_cap): β=1 everywhere is the
    plain run bit-for-bit, and a geometric ladder is deterministic."""
    g = random_regular_graph(96, 3, seed=0)
    kw = dict(n_replicas=8, seed=2, m_target=0.9, max_sweeps=300)
    plain = fused_anneal(g, _cfg(), **kw)
    unit = fused_anneal(g, _cfg(), betas=np.ones(8), **kw)
    np.testing.assert_array_equal(plain.s, unit.s)
    assert plain.accepted == unit.accepted
    ladder = fused_anneal(g, _cfg(), betas=np.geomspace(1, 16, 8), **kw)
    ladder2 = fused_anneal(g, _cfg(), betas=np.geomspace(1, 16, 8), **kw)
    np.testing.assert_array_equal(ladder.s, ladder2.s)
    assert not np.array_equal(plain.s, ladder.s)


# ---------------------------------------------------------------------------
# zero host transfers between snapshot boundaries (the tentpole claim)
# ---------------------------------------------------------------------------


def test_fused_fixed_budget_zero_host_transfers():
    """The whole fixed-budget drive loop — every chunk dispatch, the
    schedule advance, the first-passage records — runs under
    ``jax.transfer_guard_device_to_host('disallow')``: any device→host
    readback between snapshot boundaries raises. Results read back ONCE
    after the guard."""
    g = random_regular_graph(96, 3, seed=0)
    state, tables_dev, static, tables, R, W, Rp = _assemble_fused(
        g, _cfg(), n_replicas=8, seed=0, m_target=0.9, betas=None,
        tables=None,
    )
    holder = {"spec": resolve_fused_mode(
        "xla", n=g.n, W=W, chi=tables.chi, dmax=tables.dmax)}
    with jax.transfer_guard_device_to_host("disallow"):
        st = _run_plan(
            state, jnp.uint32(0), tables_dev, holder, [64] * 4,
            stop_on_first=False, sync=False, chi=tables.chi,
            static=static,
        )
    assert int(st.steps) > 0          # readback AFTER the guard


@pytest.mark.graftcheck
def test_fused_chunk_program_one_while_loop_donated():
    """The graftcheck acceptance criterion asserted live (independent of
    the committed ledger): the fused chunk program compiles to exactly
    ONE while loop — the counter RNG adds no jax.random threefry loops —
    with the state carry donated and no large baked constants."""
    from graphdyn.analysis.graftcheck import fingerprint_lowered
    from graphdyn.search.fused import lower_fused_chunk

    fp = fingerprint_lowered(lower_fused_chunk(
        random_regular_graph(48, 3, seed=0), _cfg(), n_replicas=32,
        seed=0, m_target=0.9, chunk_sweeps=4,
    ))
    assert fp["while_loop_count"] == 1, fp["op_categories"]
    assert fp["donated_params"], "state carry must be donated"
    assert fp["largest_constant_bytes"] < (1 << 20)


# ---------------------------------------------------------------------------
# kernel selection, VMEM model, fallback, refusals
# ---------------------------------------------------------------------------


def test_fused_vmem_model_and_gate():
    b1 = fused_vmem_bytes(4096, 1, 8, 3)
    assert 0 < b1 <= FUSED_VMEM_BUDGET       # the search regime fits
    assert fused_kernel_supported(4096, 1, 8, 3)
    # monotone in every axis
    assert fused_vmem_bytes(8192, 1, 8, 3) > b1
    assert fused_vmem_bytes(4096, 4, 8, 3) > b1
    assert fused_vmem_bytes(4096, 1, 16, 3) > b1
    assert fused_vmem_bytes(4096, 1, 8, 5) > b1
    # an honest False past the budget (n=1e6 is the XLA twin's job)
    assert not fused_kernel_supported(1_000_000, 4, 10, 3)


def test_fused_mode_resolution_cpu():
    kw = dict(n=4096, W=1, chi=8, dmax=3)
    assert resolve_fused_mode("auto", **kw).pallas == ("",)     # CPU
    assert resolve_fused_mode("xla", **kw).pallas == ("",)
    assert resolve_fused_mode("pallas", **kw).pallas == ("interpret",)
    with pytest.raises(ValueError, match="kernel"):
        resolve_fused_mode("fast", **kw)


def test_fused_runtime_lowering_failure_falls_back_to_xla(monkeypatch):
    """A forced-Pallas run whose kernel dies in lowering degrades to the
    XLA twin through the shared resilient_exec machinery — same results,
    and the rebuilt spec sticks for later chunks (one retry total)."""
    import graphdyn.ops.pallas_anneal as pa

    g = random_regular_graph(64, 3, seed=0)
    kw = dict(n_replicas=8, seed=1, m_target=0.9, max_sweeps=200,
              chunk_sweeps=50)
    want = fused_anneal(g, _cfg(), kernel="xla", **kw)
    calls = {"n": 0}

    def boom(*a, **k):
        calls["n"] += 1
        raise RuntimeError("Mosaic lowering failed (injected): pallas")

    monkeypatch.setattr(pa, "fused_chunk_pallas", boom)
    got = fused_anneal(g, _cfg(), kernel="pallas", **kw)
    assert got.kernel_used == "xla"          # the rebuilt spec stuck
    assert calls["n"] == 1                   # ONE failed attempt, no loop
    np.testing.assert_array_equal(want.s, got.s)
    np.testing.assert_array_equal(want.steps_to_target,
                                  got.steps_to_target)


def test_fused_validations():
    g = random_regular_graph(32, 3, seed=0)
    with pytest.raises(ValueError, match="p = c = 1"):
        fused_anneal(g, SAConfig(dynamics=DynamicsConfig(p=3, c=1)),
                     n_replicas=2)
    with pytest.raises(ValueError, match="m_target"):
        fused_anneal(g, _cfg(), n_replicas=2, m_target=1.5)
    with pytest.raises(ValueError, match="chunk_sweeps"):
        fused_anneal(g, _cfg(), n_replicas=2, chunk_sweeps=0)
    with pytest.raises(ValueError, match="max_sweeps"):
        fused_anneal(g, _cfg(), n_replicas=2, max_sweeps=0)
    with pytest.raises(ValueError, match="betas"):
        fused_anneal(g, _cfg(), n_replicas=4, betas=np.ones(3))


def test_sa_kernel_knob_refuses_pallas_with_routing():
    """models/sa gained the kernel knob: auto/xla are the serial chain;
    'pallas' is refused with a message routing to the fused annealer —
    the fused chain is a DIFFERENT Markov chain, and kernel choice moves
    throughput, never results."""
    from graphdyn.models.sa import simulated_annealing

    g = random_regular_graph(32, 3, seed=0)
    a = simulated_annealing(g, _cfg(), n_replicas=2, seed=0,
                            max_steps=200, kernel="auto")
    x = simulated_annealing(g, _cfg(), n_replicas=2, seed=0,
                            max_steps=200, kernel="xla")
    np.testing.assert_array_equal(a.s, x.s)
    with pytest.raises(ValueError, match="fused_anneal"):
        simulated_annealing(g, _cfg(), n_replicas=2, kernel="pallas")
    with pytest.raises(ValueError, match="kernel"):
        simulated_annealing(g, _cfg(), n_replicas=2, kernel="warp")


# ---------------------------------------------------------------------------
# CLI + cross-process restart reproducibility
# ---------------------------------------------------------------------------


def test_cli_fused_and_restart_reproducible(tmp_path, capsys):
    """The `fused` CLI runs end to end, and a SEPARATE process produces
    the bit-identical run (the counter RNG carries no process state) —
    the restart half of the RNG-parity satellite."""
    import subprocess
    import sys

    from graphdyn.cli import main
    from graphdyn.utils.io import load_results_npz

    out = str(tmp_path / "f.npz")
    argv = ["fused", "--n", "96", "--d", "3", "--replicas", "8",
            "--m-target", "0.9", "--max-sweeps", "300", "--seed", "5",
            "--out", out]
    rc = main(argv)
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["solver"] == "fused" and line["kernel"] == "xla"
    assert line["chi"] >= 2 and line["device_steps"] >= 0
    a = load_results_npz(out)

    out2 = str(tmp_path / "g.npz")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn"] + argv[:-1] + [out2],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    b = load_results_npz(out2)
    np.testing.assert_array_equal(a["conf"], b["conf"])
    np.testing.assert_array_equal(a["steps_to_target"],
                                  b["steps_to_target"])


def test_cli_fused_drive_ladder_flag(capsys):
    from graphdyn.cli import main

    rc = main(["fused", "--n", "64", "--d", "3", "--replicas", "4",
               "--m-target", "0.9", "--max-sweeps", "150", "--seed", "1",
               "--ladder-beta-max", "16"])
    assert rc == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["solver"] == "fused"
