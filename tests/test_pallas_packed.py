"""Interpret-mode bit-parity of the Pallas packed-dynamics kernel
(`graphdyn.ops.pallas_packed`) against the XLA packed kernel — same contract
as the fused BDCM kernel's tests: correctness is provable off-chip, the
chip decides only whether it is *faster*."""

import numpy as np
import pytest

import jax.numpy as jnp

from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.ops.packed import pack_spins, packed_rollout, unpack_spins
from graphdyn.ops.pallas_packed import (
    pallas_packed_rollout,
    pallas_packed_supported,
)


@pytest.mark.parametrize("rule", ["majority", "minority"])
@pytest.mark.parametrize("d", [3, 5])
def test_pallas_packed_matches_xla(rule, d):
    g = random_regular_graph(300, d, seed=2)
    rng = np.random.default_rng(0)
    R = 64
    sp = jnp.asarray(pack_spins(
        (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
    ))
    nbr = jnp.asarray(g.nbr)
    deg = jnp.asarray(g.deg)
    ref = packed_rollout(nbr, deg, sp, 4, rule, "stay")
    out = pallas_packed_rollout(
        nbr, g.deg, sp, 4, rule, block=128, depth=4, interpret=True
    )
    np.testing.assert_array_equal(
        unpack_spins(np.asarray(out), R), unpack_spins(np.asarray(ref), R)
    )


@pytest.mark.parametrize("rule", ["majority", "minority"])
@pytest.mark.parametrize("tie", ["stay", "change"])
def test_pallas_packed_general_matches_xla(rule, tie):
    """The general-degree kernel (v2: per-node thresholds, ghost slots,
    own-row tie-break, ghost-carried state) is bit-identical to the XLA
    kernel on ragged ER and even-degree RRG shapes — the full (rule, tie)
    matrix, including the tie paths v1 cannot reach."""
    from graphdyn.graphs import remove_isolates
    from graphdyn.ops.pallas_packed import pallas_packed_rollout_general

    for g in (
        remove_isolates(erdos_renyi_graph(150, 3.0 / 149, seed=0))[0],
        random_regular_graph(120, 4, seed=1),
    ):
        rng = np.random.default_rng(0)
        R = 64
        sp = jnp.asarray(pack_spins(
            (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
        ))
        ref = packed_rollout(
            jnp.asarray(g.nbr), jnp.asarray(g.deg), sp, 4, rule, tie
        )
        out = pallas_packed_rollout_general(
            jnp.asarray(g.nbr), jnp.asarray(g.deg), sp, 4, rule, tie,
            block=64, depth=4, interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_pallas_packed_padding_and_gates():
    # n not a multiple of block exercises the pad-row path
    g = random_regular_graph(70, 3, seed=1)
    rng = np.random.default_rng(1)
    sp = jnp.asarray(pack_spins(
        (2 * rng.integers(0, 2, size=(32, g.n)) - 1).astype(np.int8)
    ))
    ref = packed_rollout(jnp.asarray(g.nbr), jnp.asarray(g.deg), sp, 3)
    out = pallas_packed_rollout(
        jnp.asarray(g.nbr), g.deg, sp, 3, block=64, depth=4, interpret=True
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    # gates: even degree, ragged degrees, unsupported rule handling
    assert not pallas_packed_supported(np.full(10, 4), "majority", "stay")
    er = erdos_renyi_graph(60, 2.0 / 59, seed=0)
    assert not pallas_packed_supported(er.deg, "majority", "stay")
    with pytest.raises(ValueError, match="uniform odd degree"):
        pallas_packed_rollout(
            jnp.asarray(er.nbr), er.deg, sp[: er.n], 1, interpret=True
        )
