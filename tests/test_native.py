"""C++ native graph builder: validity + statistical agreement with numpy."""

import numpy as np
import pytest

from graphdyn._native import native_available
from graphdyn.graphs import erdos_renyi_graph, random_regular_graph

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain in environment"
)


def test_native_rrg_valid():
    for n, d in [(100, 3), (501, 2), (2000, 5)]:
        g = random_regular_graph(n, d, seed=9, method="native")
        assert np.all(g.deg == d)
        e = g.edges
        assert np.all(e[:, 0] != e[:, 1])
        code = np.minimum(e[:, 0], e[:, 1]) * g.n + np.maximum(e[:, 0], e[:, 1])
        assert np.unique(code).size == code.size


def test_native_er_statistics():
    n, mean_deg = 5000, 4.0
    g = erdos_renyi_graph(n, mean_deg / (n - 1), seed=3, method="native")
    assert abs(g.deg.mean() - mean_deg) < 0.3
    e = g.edges
    assert np.all(e[:, 0] != e[:, 1])
    assert np.all((e >= 0) & (e < n))
    code = np.minimum(e[:, 0], e[:, 1]) * n + np.maximum(e[:, 0], e[:, 1])
    assert np.unique(code).size == code.size


def test_native_er_degenerate():
    g0 = erdos_renyi_graph(100, 0.0, seed=1, method="native")
    assert g0.num_edges == 0
    g1 = erdos_renyi_graph(40, 1.0, seed=1, method="native")
    assert g1.num_edges == 40 * 39 // 2


def test_native_seed_determinism():
    a = random_regular_graph(200, 3, seed=42, method="native")
    b = random_regular_graph(200, 3, seed=42, method="native")
    np.testing.assert_array_equal(a.edges, b.edges)


def test_native_vs_numpy_throughput_smoke():
    # not a perf assert — just exercises the native path at a bigger size
    g = random_regular_graph(200_000, 3, seed=0, method="native")
    assert g.num_edges == 300_000
