"""Entropy plot rendering: the notebook's end artifact ("BDCM entropy plots",
`code/README.md:1`) renders headlessly from solver results."""

import numpy as np
import pytest

pytest.importorskip("matplotlib")


def _fake_grid():
    from graphdyn.models.entropy import EntropyGridResult

    L = 5
    lam = np.linspace(0, 0.4, L)
    m = np.stack([np.linspace(0.8, 0.6, L), np.linspace(0.82, 0.62, L)])
    ent = np.stack([0.17 - 0.1 * lam, 0.16 - 0.1 * lam])
    ent1 = ent + lam * m
    z = np.zeros((1, 2))
    return EntropyGridResult(
        deg=np.array([1.0]),
        ent=ent[None], m_init=m[None], ent1=ent1[None],
        nodes_isolated=z, mean_degrees=z, max_degrees=z,
        mean_degrees_total=z, counts=z,
    )


def test_plot_entropy_grid_writes_png(tmp_path):
    from graphdyn.plotting import plot_entropy_grid

    p = str(tmp_path / "curves.png")
    ax = plot_entropy_grid(_fake_grid(), save_path=p)
    assert ax is not None
    assert (tmp_path / "curves.png").stat().st_size > 0


def test_plot_entropy_curve_drops_nonfinite(tmp_path):
    from graphdyn.models.entropy import EntropyResult
    from graphdyn.plotting import plot_entropy_curve

    res = EntropyResult(
        lambdas=np.array([0.0, 0.1, 0.2]),
        ent=np.array([0.1, 0.05, -np.inf]),
        m_init=np.array([0.8, 0.7, 0.6]),
        ent1=np.array([0.1, 0.12, -np.inf]),   # last point: empty attractor
        sweeps=np.array([10, 12, 5]),
        nonconverged=0.0,
        chi=np.zeros((2, 2, 2)),
    )
    p = str(tmp_path / "curve.png")
    ax = plot_entropy_curve(res, label="deg=1", save_path=p)
    (line,) = [l for l in ax.lines if l.get_label() == "deg=1"]
    assert line.get_xdata().size == 2            # -inf point dropped
    assert (tmp_path / "curve.png").stat().st_size > 0


def test_cli_entropy_plot_flag(tmp_path):
    from graphdyn.cli import main

    p = str(tmp_path / "grid.png")
    rc = main([
        "entropy", "--n", "60", "--deg", "1.0", "--num-rep", "1",
        "--lmbd-max", "0.2", "--plot", p,
    ])
    assert rc == 0
    assert (tmp_path / "grid.png").stat().st_size > 0
