"""Persistence: npz results round-trip, atomic checkpoint, periodic saver."""

import numpy as np

from graphdyn.utils.io import (
    Checkpoint,
    PeriodicCheckpointer,
    load_results_npz,
    save_results_npz,
)


def test_results_npz_roundtrip(tmp_path):
    p = str(tmp_path / "res.npz")
    save_results_npz(
        p, mag_reached=np.array([0.5]), conf=np.ones((2, 3), np.int8), time=1.25
    )
    out = load_results_npz(p)
    assert set(out) == {"mag_reached", "conf", "time"}
    np.testing.assert_array_equal(out["conf"], np.ones((2, 3), np.int8))


def test_checkpoint_roundtrip_single_file(tmp_path):
    ck = Checkpoint(str(tmp_path / "state"))
    assert ck.load() is None
    arrays = {"chi": np.arange(6.0).reshape(2, 3), "s": np.array([1, -1], np.int8)}
    meta = {"lmbd_index": 7, "t": 123, "seed": 5}
    ck.save(arrays, meta)
    # single-file layout: arrays+meta can never be torn apart by preemption
    assert (tmp_path / "state.npz").exists()
    assert not (tmp_path / "state.json").exists()
    arrs, m = ck.load()
    assert m == meta
    np.testing.assert_array_equal(arrs["chi"], arrays["chi"])
    np.testing.assert_array_equal(arrs["s"], arrays["s"])


def test_checkpoint_rejects_object_dtype_at_save(tmp_path):
    """np.savez would pickle an object array and succeed, but the default
    allow_pickle=False load then fails — which the corruption handler would
    quarantine as 'corrupt' on every resume. Fail at write time instead."""
    ck = Checkpoint(str(tmp_path / "state"))
    ragged = np.asarray([np.arange(2), np.arange(3)], dtype=object)
    with np.testing.assert_raises(TypeError):
        ck.save({"bad": ragged}, {})
    assert ck.load() is None                     # nothing was written


def test_checkpoint_reserved_key(tmp_path):
    ck = Checkpoint(str(tmp_path / "state"))
    try:
        ck.save({"__meta__": np.zeros(1)}, {})
    except ValueError:
        pass
    else:
        raise AssertionError("reserved key must be rejected")


def test_periodic_checkpointer_throttles(tmp_path):
    pc = PeriodicCheckpointer(str(tmp_path / "pc"), interval_s=1e9)
    assert not pc.maybe_save({"x": np.zeros(1)}, {})   # within interval
    pc._last -= 2e9
    assert pc.maybe_save({"x": np.zeros(1)}, {"t": 1})
    arrs, meta = pc.ckpt.load()
    assert meta == {"t": 1}


def test_results_npz_write_is_atomic(tmp_path):
    """save_results_npz goes through temp + os.replace (same discipline as
    Checkpoint.save): np.savez's .npz-appending semantics are preserved and
    no temp file survives the write."""
    p = str(tmp_path / "res")                    # extensionless, like np.savez
    save_results_npz(p, x=np.arange(3))
    assert (tmp_path / "res.npz").exists()
    assert list(tmp_path.glob("*.tmp.npz")) == []
    np.testing.assert_array_equal(load_results_npz(p + ".npz")["x"], np.arange(3))


def test_write_json_atomic_roundtrip(tmp_path):
    from graphdyn.utils.io import write_json_atomic

    p = str(tmp_path / "doc.json")
    write_json_atomic(p, {"a": [1, 2]}, indent=1)
    import json

    with open(p) as f:
        assert json.load(f) == {"a": [1, 2]}
    assert list(tmp_path.glob("*.tmp")) == []


def test_checkpoint_load_metaless_npz(tmp_path):
    """A foreign npz without the __meta__ entry (e.g. a reference-style
    results file) loads with empty metadata instead of KeyError."""
    path = tmp_path / "foreign"
    np.savez(str(path) + ".npz", s=np.arange(4), m=np.float64(0.5))
    arrays, meta = Checkpoint(str(path)).load()
    assert meta == {}
    np.testing.assert_array_equal(arrays["s"], np.arange(4))


def test_fingerprint_omits_optional_fields_at_defaults():
    """Checkpoints written before EntropyConfig grew plateau_eps /
    plateau_patience must still resume: at their defaults the opt-in fields
    are omitted from the fingerprint, reproducing the pre-field digest
    byte-for-byte (ADVICE r04: the skip mechanism was dead code because no
    config declared `_fingerprint_optional`)."""
    import dataclasses

    from graphdyn.config import DynamicsConfig, EntropyConfig
    from graphdyn.utils.io import _fingerprint_repr, run_fingerprint

    cfg = EntropyConfig()
    r = _fingerprint_repr(cfg)
    assert "plateau" not in r

    # reconstruct the pre-field dataclass (same name, same fields minus the
    # opt-in ones) and check digest equality, nested config included
    pre_fields = [
        (f.name, f.type, f)
        for f in dataclasses.fields(cfg)
        if f.name not in EntropyConfig._fingerprint_optional
    ]
    Pre = dataclasses.make_dataclass("EntropyConfig", pre_fields)
    pre = Pre(**{
        f.name: getattr(cfg, f.name)
        for f in dataclasses.fields(cfg)
        if f.name not in EntropyConfig._fingerprint_optional
    })
    assert _fingerprint_repr(pre) == r
    assert run_fingerprint(pre) == run_fingerprint(cfg)

    # a NON-default opt-in value must change the fingerprint (it changes
    # ladder semantics, so resuming across it would be a chimera)
    tuned = EntropyConfig(plateau_eps=1e-4)
    assert run_fingerprint(tuned) != run_fingerprint(cfg)
    assert "plateau_eps" in _fingerprint_repr(tuned)

    # nested dynamics config still participates in the digest
    other = EntropyConfig(dynamics=DynamicsConfig(rule="minority"))
    assert run_fingerprint(other) != run_fingerprint(cfg)
