"""graftcheck: the jaxpr/HLO program auditor and its fingerprint ledger.

The acceptance contract (ISSUE 6): the committed
``GRAFTCHECK_FINGERPRINTS.json`` must match the live lowered programs
(structural drift fails tier-1 with a pointed message); deliberately
breaking a donation or forcing a recompile must FAIL the checks; a pure
refactor that preserves program structure must pass without a ledger
update; and the fingerprint is invariant across group extents and across
the serial↔grouped paths (the PR-3/4 identity contract restated at the
HLO level). All tests carry the ``graftcheck`` marker so
``scripts/lint.sh`` hlocheck can run the subset standalone.
"""

import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from graphdyn.analysis import graftcheck as gc

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.graftcheck


@pytest.fixture(scope="module")
def live_fps():
    """Live fingerprints for every registered entry point, computed once
    per module (each entry lowers + compiles a small canonical program)."""
    return gc.collect_fingerprints()


# ---------------------------------------------------------------------------
# the ledger gate
# ---------------------------------------------------------------------------


def test_ledger_matches_live(live_fps):
    """THE tier-1 structural gate: live programs diff clean against the
    committed ledger, and the ledger-free live rules (GC001–GC003) are
    clean too. A failing diff here means a headline program's structure
    changed — fix the regression, or (if deliberate) re-run
    ``python -m graphdyn.analysis.graftcheck --update-ledger`` and commit
    the reviewed ledger."""
    findings = []
    for name, fp in live_fps.items():
        findings.extend(
            gc.audit_fingerprint(name, fp, donates=gc.ENTRIES[name].donates)
        )
    ledger = gc.load_ledger()
    assert ledger is not None, (
        f"{gc.LEDGER_NAME} missing — run --update-ledger and commit it"
    )
    findings.extend(gc.check_ledger(live_fps, ledger))
    assert findings == [], "\n".join(
        f"{f.entry}: {f.code} {f.message}" for f in findings
    )


def test_ledger_covers_every_entry():
    ledger = gc.load_ledger()
    assert set(ledger["entries"]) == set(gc.ENTRIES)
    assert ledger["backend"] == "cpu"   # the hardware-free contract


def test_pure_refactor_passes(live_fps):
    """A structure-preserving change (here: a different graph instance of
    the same shape class — new values, same program) diffs clean against
    the ledger WITHOUT a ledger update."""
    from graphdyn.graphs import random_regular_graph
    from graphdyn.ops.bdcm import BDCMData, lower_sweep

    data = BDCMData(random_regular_graph(64, 3, seed=99), p=1, c=1)
    fp = gc.fingerprint_lowered(lower_sweep(data, damp=0.9))
    ledger = gc.load_ledger()
    assert gc.diff_fingerprints(
        "bdcm_sweep", ledger["entries"]["bdcm_sweep"], fp
    ) == []


def test_update_ledger_roundtrip(tmp_path, live_fps):
    path = tmp_path / "ledger.json"
    gc.write_ledger(live_fps, path)
    assert gc.check_ledger(live_fps, gc.load_ledger(path)) == []


def test_missing_ledger_is_a_finding(tmp_path, live_fps):
    """Fail closed: no ledger file -> every entry is a GC100 finding, not
    a silent pass."""
    findings = gc.check_ledger(
        live_fps, gc.load_ledger(tmp_path / "absent.json")
    )
    assert {f.code for f in findings} == {"GC100"}
    assert len(findings) == len(live_fps)


# ---------------------------------------------------------------------------
# deliberate structural breaks MUST fail, with pointed messages
# ---------------------------------------------------------------------------


def test_broken_donation_fails(live_fps):
    """Deliberately losing a donation in a headline entry point fails the
    ledger diff with a message naming the double-buffering consequence."""
    ledger = gc.load_ledger()
    broken = dict(live_fps["sa_group_loop"])
    broken["donated_params"] = []        # the donation is gone
    findings = gc.diff_fingerprints(
        "sa_group_loop", ledger["entries"]["sa_group_loop"], broken
    )
    assert any(f.code == "GC104" for f in findings)
    msg = next(f.message for f in findings if f.code == "GC104")
    assert "donation LOST" in msg and "double-buffered" in msg


def test_new_op_category_fails(live_fps):
    """A structurally new kind of op (e.g. a custom-call appearing in a
    program that never had one) fails the diff."""
    ledger = gc.load_ledger()
    drifted = json.loads(json.dumps(live_fps["packed_rollout"]))
    drifted["op_categories"]["custom-call"] = 2
    findings = gc.diff_fingerprints(
        "packed_rollout", ledger["entries"]["packed_rollout"], drifted
    )
    assert any(
        f.code == "GC101" and "custom-call" in f.message for f in findings
    )


def test_while_loop_change_fails(live_fps):
    ledger = gc.load_ledger()
    drifted = dict(live_fps["entropy_cell_chunk"])
    drifted["while_loop_count"] = drifted["while_loop_count"] + 1
    findings = gc.diff_fingerprints(
        "entropy_cell_chunk", ledger["entries"]["entropy_cell_chunk"],
        drifted,
    )
    assert any(
        f.code == "GC106" and "loop structure" in f.message for f in findings
    )


def test_constant_blowup_fails(live_fps):
    ledger = gc.load_ledger()
    drifted = dict(live_fps["bdcm_sweep"])
    drifted["largest_constant_bytes"] = 8 << 20
    findings = gc.diff_fingerprints(
        "bdcm_sweep", ledger["entries"]["bdcm_sweep"], drifted
    )
    assert any(f.code == "GC105" for f in findings)


def test_fusion_jump_fails_and_jitter_passes(live_fps):
    ledger = gc.load_ledger()
    fp = live_fps["hpr_group_loop"]
    base = ledger["entries"]["hpr_group_loop"]
    jitter = dict(fp, fusion_count=fp["fusion_count"] + 1)
    assert gc.diff_fingerprints("hpr_group_loop", base, jitter) == []
    jump = dict(fp, fusion_count=2 * fp["fusion_count"] + 4)
    assert any(
        f.code == "GC103"
        for f in gc.diff_fingerprints("hpr_group_loop", base, jump)
    )


# ---------------------------------------------------------------------------
# GC001–GC003: the live (ledger-free) rules
# ---------------------------------------------------------------------------


def test_gc001_unhonored_donation():
    """A declared donation the compiler cannot use (no output shares the
    input's shape/dtype) leaves no input/output alias — GC001."""
    f = jax.jit(
        lambda x: (x.astype(jnp.int32) * 2).sum(), donate_argnums=(0,)
    )
    fp = gc.fingerprint_lowered(f.lower(jnp.ones((64,), jnp.float32)))
    assert fp["donated_params"] == []
    findings = gc.audit_fingerprint("probe", fp, donates=True)
    assert [f.code for f in findings] == ["GC001"]
    assert "double-buffered" in findings[0].message


def test_gc001_honored_donation_is_clean():
    f = jax.jit(lambda x: x * 2, donate_argnums=(0,))
    fp = gc.fingerprint_lowered(f.lower(jnp.ones((64,), jnp.float32)))
    assert fp["donated_params"] == [0]
    assert gc.audit_fingerprint("probe", fp, donates=True) == []


def test_gc002_f64_promotion_caught():
    """Under x64, a stray np.float64 scalar widens an f32 chain — caught
    at the jaxpr level with the offending primitives named."""
    from jax.experimental import enable_x64

    with enable_x64():
        def promoted(x):
            return x * np.float64(2.0)  # graftlint: disable=GD004  the bad example under test

        def clean(x):
            return x * jnp.float32(2.0)

        x = jnp.ones((8,), jnp.float32)
        findings = gc.check_no_f64(promoted, x)
        assert [f.code for f in findings] == ["GC002"]
        assert "promotion" in findings[0].message
        assert gc.check_no_f64(clean, x) == []


def test_gc002_f64_inputs_are_legitimate():
    """An entry point that takes f64 INPUTS (the reference-faithful x64
    BDCM path) is not a promotion — no finding."""
    from jax.experimental import enable_x64

    with enable_x64():
        # graftlint: disable-next-line=GD004  the f64-input case under test
        x = jnp.ones((8,), jnp.float64)
        assert gc.check_no_f64(lambda v: v * 2.0, x) == []


def test_gc003_large_baked_constant():
    # random values: an all-ones table would constant-fold into a
    # broadcast(scalar) and never appear as a large literal
    big = np.random.default_rng(0).random((600, 600)).astype(np.float32)
    f = jax.jit(lambda x: x + jnp.asarray(big))
    fp = gc.fingerprint_lowered(f.lower(jnp.ones((600, 600), jnp.float32)))
    assert fp["largest_constant_bytes"] >= big.nbytes
    findings = gc.audit_fingerprint("probe", fp, donates=False)
    assert any(f.code == "GC003" for f in findings)


def test_headline_entries_bake_no_large_constants(live_fps):
    for name, fp in live_fps.items():
        assert fp["largest_constant_bytes"] <= gc.LARGE_CONSTANT_BYTES, name


# ---------------------------------------------------------------------------
# GC004: the recompile guard
# ---------------------------------------------------------------------------


def test_gc004_forced_recompile_detected():
    @jax.jit
    def _gc004_probe(x):
        return x * 3

    with gc.RecompileWatch() as watch:
        _gc004_probe(jnp.ones((16,)))
        _gc004_probe(jnp.ones((16,)))       # cache hit: no event
        _gc004_probe(jnp.ones((32,)))       # new signature
    sigs = watch.signatures("_gc004_probe")
    assert len(sigs) == 2
    findings = gc.check_recompiles(watch, {"_gc004_probe": 1})
    assert [f.code for f in findings] == ["GC004"]
    assert "recompiles" in findings[0].message
    # within budget (two legitimate shape classes): clean
    assert gc.check_recompiles(watch, {"_gc004_probe": 2}) == []


def test_gc004_grouped_driver_compiles_once_per_shape_class():
    """The headline contract: a grouped driver run at ONE shape class
    compiles its loop program at most once — a second run at the same
    shapes (different seeds) adds no signature; a different group extent
    is a new shape class and would."""
    from graphdyn.config import DynamicsConfig, SAConfig
    from graphdyn.graphs import random_regular_graph
    from graphdyn.models.sa import prepare_sa_inputs
    from graphdyn.pipeline.sa_group import run_sa_group

    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))

    def run(seed0):
        graphs = [
            random_regular_graph(32, 3, seed=seed0 + k) for k in range(2)
        ]
        preps = [
            prepare_sa_inputs(g, cfg, n_replicas=1, seed=seed0 + k,
                              max_steps=40)
            for k, g in enumerate(graphs)
        ]
        run_sa_group(graphs, preps, [seed0, seed0 + 1], cfg, group_size=2,
                     chunk_steps=20)

    with gc.RecompileWatch() as watch:
        run(0)
        first = len(watch.signatures("_sa_group_loop"))
        run(10)                              # same shape class
    assert len(watch.signatures("_sa_group_loop")) == first <= 1
    assert gc.check_recompiles(watch, {"_sa_group_loop": 1}) == []


# ---------------------------------------------------------------------------
# fingerprint invariance: the PR-3/4 identity contract at the HLO level
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("entry", ["entropy_cell_chunk", "hpr_group_loop"])
def test_fingerprint_invariant_across_group_extents(entry):
    """``entropy_sweep``/``hpr_solve`` run the G=1 instance of the same
    group program the drivers run at G>1: the structural fingerprint must
    diff clean across G ∈ {1, 2, 8} in every direction (shape-sensitive
    fields like fusion root shapes are informational, not gated)."""
    fps = {G: gc.fingerprint_lowered(gc.lower_entry(entry, G=G))
           for G in (1, 2, 8)}
    for a in (1, 2, 8):
        for b in (1, 2, 8):
            if a == b:
                continue
            findings = gc.diff_fingerprints(f"{entry}@G{a}->{b}",
                                            fps[a], fps[b])
            assert findings == [], "\n".join(
                f"{f.entry}: {f.code} {f.message}" for f in findings
            )


def test_sa_fingerprint_invariant_across_group_extents():
    """SA holds the same contract for G ∈ {2, 8}. (At G=1 XLA fully
    unrolls the bounded chunk loop on CPU — a real structural difference
    of the canonical G=2 ledger entry's shape class, which is why the
    ledger pins G=2 and the serial driver path is the G=1 *instance*, not
    a separate fingerprint row.)"""
    fps = {G: gc.fingerprint_lowered(gc.lower_entry("sa_group_loop", G=G))
           for G in (2, 8)}
    assert gc.diff_fingerprints("sa@2->8", fps[2], fps[8]) == []
    assert gc.diff_fingerprints("sa@8->2", fps[8], fps[2]) == []


def test_serial_ladder_reuses_the_group_program():
    """``entropy_sweep`` (the serial path) advances through the SAME
    compiled chunk program a hand-built G=1 ``EntropyCellExec`` uses: the
    second does not compile ``_cell_chunk_exec`` again — one program
    family, serial == grouped at the compile-cache level, the recompile
    guard's positive control."""
    from graphdyn.config import DynamicsConfig, EntropyConfig
    from graphdyn.graphs import random_regular_graph
    from graphdyn.models.entropy import entropy_sweep
    from graphdyn.ops.bdcm import BDCMData
    from graphdyn.pipeline.entropy_group import EntropyCellExec

    cfg = EntropyConfig(
        dynamics=DynamicsConfig(p=1, c=1), lmbd_max=0.1, lmbd_step=0.1,
        max_sweeps=60, eps=1e-3,
    )
    g = random_regular_graph(40, 3, seed=7)
    with gc.RecompileWatch() as watch:
        entropy_sweep(g, cfg, seed=0)
        first = len(watch.signatures("_cell_chunk_exec"))
        data = BDCMData(g, p=1, c=1, rule=cfg.dynamics.rule,
                        tie=cfg.dynamics.tie)
        ex = EntropyCellExec([(data, g.n, 0)], cfg, kernel="xla")
        chi = ex.stack_chi([data.init_messages(0)])
        ex.fixed_point_chunk(
            chi, jnp.zeros(1, jnp.float32), jnp.ones(1, bool),
            jnp.full(1, jnp.inf, jnp.float32), jnp.zeros(1, jnp.int32),
        )
    assert len(watch.signatures("_cell_chunk_exec")) == first
    assert gc.check_recompiles(watch, {"_cell_chunk_exec": 1}) == []


# ---------------------------------------------------------------------------
# determinism + CLI
# ---------------------------------------------------------------------------


def test_fingerprint_deterministic(live_fps):
    """Two independent lowerings of the same entry fingerprint
    identically (the property the committed ledger rests on)."""
    again = gc.fingerprint_lowered(gc.lower_entry("bdcm_sweep"))
    assert again == live_fps["bdcm_sweep"]


def test_cli_json_is_one_document_stdout_only():
    """``python -m graphdyn.analysis.graftcheck --format=json`` emits
    exactly ONE JSON document on stdout (findings + fingerprints) with
    every diagnostic on stderr — the CI pipe contract."""
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis.graftcheck",
         "--format=json", "--entries", "bdcm_sweep"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    doc = json.loads(proc.stdout)        # the whole stdout parses
    assert proc.returncode == 0, doc["findings"]
    assert doc["findings"] == []
    assert set(doc["fingerprints"]) == {"bdcm_sweep"}
    assert "graftcheck" in proc.stderr   # diagnostics went to stderr
    assert "graftcheck" not in proc.stdout


def test_cli_unknown_entry_rejected():
    proc = subprocess.run(
        [sys.executable, "-m", "graphdyn.analysis.graftcheck",
         "--entries", "nope"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2
    assert "unknown entries" in proc.stderr


def test_bench_fingerprint_diff():
    """The benchcheck hook: same-backend rows diff with the ledger bands;
    cross-backend rows and pre-fingerprint rounds produce nothing."""
    row = {"backend": "cpu", "entries": {
        "packed_rollout": {
            "op_categories": {"elementwise": 100, "layout": 120},
            "fusion_count": 12, "while_loop_count": 0,
            "donated_params": [], "largest_constant_bytes": 4,
        },
    }}
    same = json.loads(json.dumps(row))
    assert gc.diff_bench_fingerprints(row, same) == []
    tpu_row = dict(same, backend="tpu")
    assert gc.diff_bench_fingerprints(row, tpu_row) == []
    assert gc.diff_bench_fingerprints(None, row) == []
    assert gc.diff_bench_fingerprints({}, row) == []
    drift = json.loads(json.dumps(row))
    drift["entries"]["packed_rollout"]["while_loop_count"] = 3
    findings = gc.diff_bench_fingerprints(row, drift)
    assert [f.code for f in findings] == ["GC106"]


def test_bench_drift_blessed_by_ledger(live_fps):
    """benchcheck's update path: a row that drifted from the previous
    ROUND but matches the committed LEDGER is a deliberate, blessed
    change (round artifacts are immutable — without this, a blessed
    restructure would leave the gate permanently red)."""
    compact = {
        name: {k: fp[k] for k in gc._COMPACT_FIELDS}
        for name, fp in live_fps.items()
    }
    row = {"backend": "cpu", "entries": compact}
    assert gc.bench_drift_blessed(row)                    # matches ledger
    unblessed = json.loads(json.dumps(row))
    unblessed["entries"]["bdcm_sweep"]["while_loop_count"] += 2
    assert not gc.bench_drift_blessed(unblessed)          # ledger disagrees
    assert not gc.bench_drift_blessed(dict(row, backend="tpu"))
    assert not gc.bench_drift_blessed({})
    assert not gc.bench_drift_blessed(row, ledger={})     # no ledger: red


# ---------------------------------------------------------------------------
# the runtime host-aliasing sanitizer
# ---------------------------------------------------------------------------


class TestAliasSanitizer:
    def test_race_is_deterministic_failure(self):
        from graphdyn.analysis.sanitize import AliasRaceError, alias_sanitizer

        with pytest.raises(AliasRaceError) as exc:
            with alias_sanitizer():
                buf = np.zeros(128, np.float32)
                dev = jnp.asarray(buf)
                (dev + 1).block_until_ready()
                buf[0] = 5.0              # mutation inside the alias window
        assert "test_graftcheck.py" in str(exc.value)   # names the crossing
        assert "jnp.array" in str(exc.value)            # and the fix

    def test_copy_crossing_is_clean(self):
        from graphdyn.analysis.sanitize import alias_sanitizer

        with alias_sanitizer():
            buf = np.zeros(128, np.float32)
            jnp.array(buf)                # the PR-4 fix: explicit copy
            buf[0] = 5.0

    def test_drop_before_mutate_is_clean(self):
        import gc as pygc

        from graphdyn.analysis.sanitize import alias_sanitizer

        with alias_sanitizer():
            buf = np.zeros(64, np.float32)
            dev = jnp.asarray(buf)
            float(dev.sum())
            del dev                       # alias window closed
            pygc.collect()
            buf[0] = 1.0

    def test_provable_copy_crossing_not_tracked(self):
        """A dtype-converting asarray ALWAYS copies — mutating the source
        afterwards is legitimate buffer reuse, not a race (a false
        AliasRaceError here would break every sanitized driver that ships
        a converted staging buffer)."""
        from graphdyn.analysis.sanitize import alias_sanitizer

        with alias_sanitizer() as san:
            buf = np.zeros(64, np.float32)
            dev = jnp.asarray(buf, jnp.int32)     # conversion: copy
            dev.block_until_ready()
            buf[0] = 7.0
            assert san.records == []

    def test_dead_records_released(self):
        """Verified records are pruned at array finalization (an
        hours-long sanitized run must not pin every staging buffer it
        ever crossed)."""
        import gc as pygc

        from graphdyn.analysis.sanitize import alias_sanitizer

        with alias_sanitizer() as san:
            for _ in range(5):
                buf = np.zeros(256, np.float32)
                dev = jnp.asarray(buf)
                dev.block_until_ready()
                del dev
            pygc.collect()
            assert san.records == []

    def test_traced_crossing_not_tracked(self):
        """Inside jit tracing the crossing yields a Tracer (which IS a
        jax.Array instance) consumed at trace time — no alias survives
        into execution, so it must not be tracked (per-closure-constant
        digest cost for a window that closes before any mutation)."""
        import jax

        from graphdyn.analysis.sanitize import alias_sanitizer

        with alias_sanitizer() as san:
            host_table = np.arange(32, dtype=np.float32)

            @jax.jit
            def f(x):
                return x + jnp.asarray(host_table)

            f(jnp.ones(32, jnp.float32)).block_until_ready()
            assert san.records == []

    def test_readonly_buffer_not_tracked(self):
        from graphdyn.analysis.sanitize import alias_sanitizer

        with alias_sanitizer() as san:
            buf = np.zeros(32, np.float32)
            buf.setflags(write=False)
            jnp.asarray(buf)
            assert san.records == []

    def test_env_gated(self, monkeypatch):
        from graphdyn.analysis.sanitize import maybe_alias_sanitizer

        monkeypatch.delenv("GRAPHDYN_SANITIZE", raising=False)
        with maybe_alias_sanitizer() as san:
            assert san is None
        monkeypatch.setenv("GRAPHDYN_SANITIZE", "alias")
        with maybe_alias_sanitizer() as san:
            assert san is not None

    def test_unpatched_after_exit(self):
        from graphdyn.analysis.sanitize import alias_sanitizer

        before = jnp.asarray
        with alias_sanitizer():
            assert jnp.asarray is not before
        assert jnp.asarray is before

    def test_not_reentrant(self):
        from graphdyn.analysis.sanitize import alias_sanitizer

        with alias_sanitizer():
            with pytest.raises(RuntimeError, match="re-entrant"):
                with alias_sanitizer():
                    pass

    def test_grouped_entropy_ladder_clean_under_sanitizer(self):
        """The PR-4 fix regression: the grouped entropy grid's host→device
        crossings all copy, so a full grouped ladder run is sanitizer-clean
        (before the fix, run_cell_ladder's λ staging aliased a buffer it
        then mutated — exactly what this would catch)."""
        from graphdyn.analysis.sanitize import alias_sanitizer
        from graphdyn.config import DynamicsConfig, EntropyConfig
        from graphdyn.models.entropy import entropy_grid

        cfg = EntropyConfig(
            dynamics=DynamicsConfig(p=1, c=1), lmbd_max=0.2, lmbd_step=0.1,
            num_rep=2, max_sweeps=100, eps=1e-3,
        )
        with alias_sanitizer():
            entropy_grid(24, np.asarray([1.0]), cfg, seed=0, group_size=2)


# ---------------------------------------------------------------------------
# the composed streamed x sharded exchange entry (PR 20)
# ---------------------------------------------------------------------------


def test_streamed_halo_fingerprint_structure(live_fps):
    """The composed engine's per-step exchange program: the donated hub
    carry survives compilation, collectives are present (the hub
    bit-plane ring + the ppermute slab schedule), and the program never
    deoptimizes into a full-state gather — the GD013 contract restated
    at the HLO level."""
    fp = live_fps["streamed_halo"]
    assert "unsupported" not in fp, fp
    assert fp["donated_params"], "the hub carry must stay donated"
    assert fp["op_categories"].get("collective", 0) > 0
    txt = gc.lower_entry("streamed_halo").compile().as_text()
    assert "collective-permute" in txt or "collective_permute" in txt
    assert "all-gather" not in txt and "all_gather" not in txt
    assert "all-reduce" not in txt and "all_reduce" not in txt


def test_streamed_halo_unsupported_on_one_device(monkeypatch):
    """A 1-device process cannot lower the P=2 composed program: the
    entry raises UnsupportedEntry with the force-8-devices hint, and the
    collector records a skip-with-reason — never a silent absence."""
    import graphdyn.parallel.mesh as mesh_mod

    def no_pool(k):
        raise RuntimeError(f"need {k} devices, have 1")

    monkeypatch.setattr(mesh_mod, "device_pool", no_pool)
    with pytest.raises(gc.UnsupportedEntry,
                       match="xla_force_host_platform_device_count"):
        gc.lower_entry("streamed_halo")
    fps = gc.collect_fingerprints(["streamed_halo"])
    assert "xla_force_host_platform_device_count" in \
        fps["streamed_halo"]["unsupported"]
