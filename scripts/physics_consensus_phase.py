#!/usr/bin/env python3
"""Degree dependence of the consensus threshold: m_c(c) on ER.

Context (RESULTS_r05.md): ER c=6 tips to consensus at m_c ≈ 0.010 while
random-regular graphs freeze until m(0) ≈ 0.44–0.54 — the difference is
degree HETEROGENEITY (hubs break local voting deadlocks), not density.
This script maps the interpolation — and finds the threshold is a
SPARSITY phenomenon (measured, N=1e5, 2000-step budget): at c=6 a clean
m_c ≈ 0.0099; by c=8 the unbiased (m0=0) baseline already orders
spontaneously 47% of the time, and at c ≥ 11 essentially always — there
is no threshold left to cross. The meaningful pair of observables is
therefore the curve family plus the spontaneous-ordering probability
P(consensus | m0=0) vs c; m_c is reported only where the 0.5-crossing is
meaningfully above the baseline (a crossing computed through a ≈0.5
baseline, as at c=8, is an artifact of the definition, flagged not
plotted).

Restricted to c ≥ 6 so the near-consensus criterion (|m_final| ≥ 0.99,
whole-graph) stays meaningful — at smaller c the non-giant component
fraction alone approaches 1% (a c=5 probe measured the criterion
saturating near zero for exactly this reason). Needs the large-N tier: at
n=5000 the unbiased fluctuation baseline exceeds 0.5 even at c=6.

Usage:
  python scripts/physics_consensus_phase.py OUT_JSON [OUT_PNG] [--full]

Same wedge protection as the other capture scripts.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import benchmarks.common  # noqa: F401 — repo root + platform forcing

C_GRID = (6.0, 8.0, 11.0, 16.0, 22.0)
M0_GRID = (0.0, 0.001, 0.002, 0.003, 0.005, 0.008, 0.012, 0.018, 0.026)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("out_json")
    ap.add_argument("out_png", nargs="?", default=None)
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--replot", action="store_true",
                    help="render OUT_PNG from an existing OUT_JSON")
    a = ap.parse_args()

    if a.replot:
        if not a.out_png:
            ap.error("--replot requires OUT_PNG (it only renders)")
        with open(a.out_json) as f:
            doc = json.load(f)
        curves = doc["curves"]
    else:
        from benchmarks.common import guarded_capture_init

        relay_note = guarded_capture_init()

        from graphdyn.models.consensus import (
            consensus_curve_ensemble,
            consensus_ensemble_doc,
            m_half,
        )

        n, R, max_steps, seeds = ((100_000, 256, 2000, (0, 1)) if a.full
                                  else (20_000, 128, 500, (0,)))
        t0 = time.time()
        curves = []
        for c in C_GRID:
            per_seed, agg = consensus_curve_ensemble(
                n, R, M0_GRID, max_steps, c=c, graph_seeds=seeds,
            )
            mc = m_half(agg)
            curves.append({
                "c": c, "m_c": mc,
                **consensus_ensemble_doc(n, per_seed, agg, c=c),
            })
            print(f"c={c:g}: m_c={mc} | " + " ".join(
                f"{r['m0']:g}:{r['consensus_fraction_mean']:.2f}"
                for r in agg), flush=True)

        doc = {
            "what": ("consensus threshold vs ER mean degree at fixed N: "
                     "the threshold is a SPARSITY phenomenon — clean "
                     "m_c at c=6, spontaneous unbiased ordering by c>=8"),
            "c_grid": list(C_GRID),
            "n": n, "replicas": R, "max_steps": max_steps,
            "backend": curves[0]["backend"],
            "elapsed_s": round(time.time() - t0, 1),
            "curves": curves,
            **({"relay": relay_note} if relay_note else {}),
        }

    # m_c is only a threshold when it clears the unbiased baseline: a
    # 0.5-crossing computed through a ~0.5 baseline (c=8) is an artifact
    # of the definition, not a barrier — keep it out of m_c_by_c and
    # record it separately
    doc["m_c_by_c"] = {}
    doc["baseline_m0_0_by_c"] = {}
    for cv in curves:
        base = cv["rows"][0]["consensus_fraction_mean"]
        doc["baseline_m0_0_by_c"][str(cv["c"])] = base
        doc["m_c_by_c"][str(cv["c"])] = (
            cv["m_c"] if cv["m_c"] is not None and base < 0.25 else None
        )
    if not a.replot:
        # atomic: the measured sweep is expensive — a crash mid-dump must
        # not destroy it (and --replot never rewrites its source at all)
        import os as _os

        tmp = a.out_json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        _os.replace(tmp, a.out_json)
        print(f"wrote {a.out_json} (m_c_by_c={doc['m_c_by_c']}, "
              f"baseline={doc['baseline_m0_0_by_c']})")

    if a.out_png:
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(9.6, 3.8), dpi=120)
        for cv in curves:
            agg = cv["rows"]
            ax1.errorbar(
                [r["m0"] for r in agg],
                [r["consensus_fraction_mean"] for r in agg],
                yerr=[r["consensus_fraction_std"] or 0.0 for r in agg],
                fmt="o-", ms=3.5, lw=1.1, capsize=2, label=f"c={cv['c']:g}",
            )
        ax1.set_xlabel("initial magnetization m(0)")
        ax1.set_ylabel("consensus fraction")
        ax1.set_title(f"ER, N={doc['n']:,}, R={doc['replicas']}", fontsize=9)
        ax1.legend(frameon=False, fontsize=7)
        cs = [cv["c"] for cv in curves]
        ax2.plot(cs, [doc["baseline_m0_0_by_c"][str(c)] for c in cs],
                 "s-", ms=5, lw=1.2, color="tab:orange",
                 label="P(consensus | m(0)=0) — spontaneous ordering")
        for c in cs:
            mc = doc["m_c_by_c"][str(c)]
            if mc is not None:
                ax2.annotate(f"$m_c$={mc:.4f}", (c, 0.05),
                             fontsize=7, ha="center")
        ax2.set_xlabel("ER mean degree c")
        ax2.set_ylabel("fraction")
        ax2.set_ylim(-0.05, 1.05)
        ax2.set_title("the threshold melts with density:\n"
                      "by c≥11 unbiased inits order anyway", fontsize=9)
        ax2.legend(frameon=False, fontsize=7)
        fig.tight_layout()
        fig.savefig(a.out_png)
        print(f"wrote {a.out_png}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
