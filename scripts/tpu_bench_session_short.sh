#!/bin/bash
# Abbreviated chip session for a late relay recovery: headline bench +
# gather A/B/C/D + DMA probe only (~30-60 min), so it cannot collide with
# the driver's own round-end bench the way the multi-hour full session
# would. Usage: bash scripts/tpu_bench_session_short.sh [outdir]
set -u
cd "$(dirname "$0")/.."
OUT="${1:-/tmp/tpu_session_short}"
mkdir -p "$OUT"

echo "[tpu-short] headline bench ..." >&2
timeout 1500 python bench.py > "$OUT/bench_headline.json" 2> "$OUT/bench_headline.err"
echo "[tpu-short] bench rc=$? $(tail -c 300 "$OUT/bench_headline.json")" >&2

echo "[tpu-short] gather experiment ..." >&2
timeout 1200 python scripts/packed_gather_experiment.py \
    > "$OUT/gather_experiment.jsonl" 2> "$OUT/gather_experiment.err"
echo "[tpu-short] gather rc=$?" >&2

echo "[tpu-short] pallas random-row gather probe ..." >&2
timeout 900 python scripts/pallas_gather_probe.py \
    > "$OUT/pallas_gather_probe.jsonl" 2> "$OUT/pallas_gather_probe.err"
echo "[tpu-short] probe rc=$?" >&2

# Merge into the round doc (the watcher may fire near round end with
# nobody around to collect by hand), and self-report completion: this
# session produces neither configs_tpu.json nor physics_tpu.json, so the
# watcher's default done-check needs the marker to stop refiring.
echo "[tpu-short] merging artifacts into the round doc ..." >&2
python scripts/collect_tpu_session.py "$OUT" BENCH_CONFIGS_r04.json >&2
echo "[tpu-short] collect rc=$?" >&2
touch "$OUT/.short_session_done"

echo "[tpu-short] done; artifacts in $OUT" >&2
