#!/bin/bash
# Abbreviated chip session for a late relay recovery: headline bench +
# gather A/B/C/D + DMA probe only (~30-60 min), so it cannot collide with
# the driver's own round-end bench the way the multi-hour full session
# would. Idempotent per stage (see _session_lib.sh).
# Usage: bash scripts/tpu_bench_session_short.sh [outdir]
set -u
cd "$(dirname "$0")/.."
. scripts/_session_lib.sh
OUT="${1:-/tmp/tpu_session_short}"
mkdir -p "$OUT"

if headline_ok "$OUT/bench_headline.json"; then
    echo "[tpu-short] headline bench already captured; skipping" >&2
else
    echo "[tpu-short] headline bench ..." >&2
    timeout 1500 python bench.py > "$OUT/bench_headline.json" 2> "$OUT/bench_headline.err"
    echo "[tpu-short] bench rc=$? $(tail -c 300 "$OUT/bench_headline.json")" >&2
fi

if rows_ok "$OUT/gather_experiment.jsonl"; then
    echo "[tpu-short] gather experiment already captured; skipping" >&2
else
    echo "[tpu-short] gather experiment ..." >&2
    timeout 1200 python scripts/packed_gather_experiment.py \
        > "$OUT/gather_experiment.jsonl" 2> "$OUT/gather_experiment.err"
    echo "[tpu-short] gather rc=$?" >&2
fi

if rows_ok "$OUT/pallas_gather_probe.jsonl"; then
    echo "[tpu-short] pallas gather probe already captured; skipping" >&2
else
    echo "[tpu-short] pallas random-row gather probe ..." >&2
    timeout 900 python scripts/pallas_gather_probe.py \
        > "$OUT/pallas_gather_probe.jsonl" 2> "$OUT/pallas_gather_probe.err"
    echo "[tpu-short] probe rc=$?" >&2
fi

collect_round "$OUT" tpu-short

# Self-report completion ONLY when the session's key artifact is really
# in hand: this session produces neither configs_tpu.json nor
# physics_tpu.json, so the watcher's done-check relies on this marker —
# and a cut-short session must leave refires available.
if headline_ok "$OUT/bench_headline.json"; then
    touch "$OUT/.short_session_done"
fi

echo "[tpu-short] done; artifacts in $OUT" >&2
