#!/bin/bash
# Abbreviated chip session for a late relay recovery: headline bench +
# Pallas validation + consensus physics (~30-50 min), so it cannot collide
# with the driver's own round-end bench the way the multi-hour full session
# would. Idempotent per stage (see _session_lib.sh).
# Usage: bash scripts/tpu_bench_session_short.sh [outdir]
set -u
cd "$(dirname "$0")/.."
. scripts/_session_lib.sh
OUT="${1:-/tmp/tpu_session_short}"
mkdir -p "$OUT"

if headline_ok "$OUT/bench_headline.json"; then
    echo "[tpu-short] headline bench already captured; skipping" >&2
else
    echo "[tpu-short] headline bench ..." >&2
    BENCH_INIT_BUDGET_S=120 timeout 1500 \
        python bench.py > "$OUT/bench_headline.json" 2> "$OUT/bench_headline.err"
    echo "[tpu-short] bench rc=$? $(tail -c 300 "$OUT/bench_headline.json")" >&2
fi

if json_ok "$OUT/PALLAS_TPU.json"; then
    echo "[tpu-short] pallas validation already captured; skipping" >&2
else
    echo "[tpu-short] pallas on-chip validation ..." >&2
    GRAPHDYN_FORCE_PLATFORM=axon timeout 1200 \
        python scripts/pallas_tpu_validate.py \
        > "$OUT/pallas_validate.log" 2>&1
    rc=$?
    echo "[tpu-short] pallas validate rc=$rc" >&2
    [ $rc -eq 0 ] && cp -f PALLAS_TPU.json "$OUT/PALLAS_TPU.json"
fi

if chip_doc_ok "$OUT/consensus_tpu.json"; then
    echo "[tpu-short] consensus physics already captured; skipping" >&2
else
    echo "[tpu-short] ER-majority consensus physics (m0 sweep) ..." >&2
    # single instance: the late-recovery session is time-boxed, and one
    # chip-labeled instance beats three lost to the timeout (no resume)
    GRAPHDYN_FORCE_PLATFORM=axon timeout 1200 \
        python scripts/physics_consensus.py \
        "$OUT/consensus_tpu.json" "$OUT/consensus_tpu.png" --full \
        --instances 1 \
        > "$OUT/consensus_tpu.log" 2>&1
    echo "[tpu-short] consensus rc=$?" >&2
fi

collect_round "$OUT" tpu-short

# Self-report completion ONLY when ALL of this session's artifacts are
# really in hand: this session produces no configs_tpu.json /
# physics_tpu.json, so the watcher's done-check relies on this marker —
# and a session cut short during ANY stage must leave refires available.
if headline_ok "$OUT/bench_headline.json" \
        && json_ok "$OUT/PALLAS_TPU.json" \
        && chip_doc_ok "$OUT/consensus_tpu.json"; then
    touch "$OUT/.short_session_done"
fi

echo "[tpu-short] done; artifacts in $OUT" >&2
