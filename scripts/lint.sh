#!/usr/bin/env bash
# The repo's lint/type/invariant gate (ARCHITECTURE.md "Static analysis &
# contracts"). Three layers, strictest last:
#
#   1. ruff   — style/bug-pattern lint (config in pyproject.toml)
#   2. mypy   — types on the layers with annotations worth checking
#   3. graftlint — the JAX/TPU-invariant linter (python -m graphdyn.analysis);
#                  ALWAYS runs (stdlib-only) and always gates
#
# ruff/mypy are optional dependencies (pyproject [dev] extra): when absent
# from the environment they are SKIPPED WITH A NOTICE, not silently — the
# container that runs the tier-1 gate does not ship them, and the gate must
# not demand installs. graftlint is the layer that can never be absent.
#
# Usage: scripts/lint.sh            # whole package
#        scripts/lint.sh PATH...    # specific files/dirs (graftlint only
#                                   # narrows; ruff/mypy keep their scope)
set -u
cd "$(dirname "$0")/.."

fail=0

if command -v ruff >/dev/null 2>&1 || python -c 'import ruff' 2>/dev/null; then
    echo "== ruff =="
    if command -v ruff >/dev/null 2>&1; then
        ruff check graphdyn/ benchmarks/ tests/ scripts/*.py __graft_entry__.py bench.py || fail=1
    else
        python -m ruff check graphdyn/ benchmarks/ tests/ scripts/*.py __graft_entry__.py bench.py || fail=1
    fi
else
    echo "== ruff: not installed — SKIPPED (pip install ruff to enable) =="
fi

if python -c 'import mypy' 2>/dev/null; then
    echo "== mypy (graphdyn/analysis, graphdyn/ops) =="
    python -m mypy graphdyn/analysis/ graphdyn/ops/ || fail=1
elif command -v mypy >/dev/null 2>&1; then
    echo "== mypy (graphdyn/analysis, graphdyn/ops) =="
    mypy graphdyn/analysis/ graphdyn/ops/ || fail=1
else
    echo "== mypy: not installed — SKIPPED (pip install mypy to enable) =="
fi

echo "== graftlint =="
# default scope: the package AND scripts/ — capture scripts persist round
# artifacts, so GD007 (atomic-write discipline) gates there too
if [ "$#" -eq 0 ]; then
    python -m graphdyn.analysis graphdyn/ scripts/ --format=text || fail=1
else
    python -m graphdyn.analysis "$@" --format=text || fail=1
fi

# 4. faultcheck — the fault-injection test subset standalone (pytest -m
#    faultinject): every recovery path in graphdyn/resilience must survive
#    its injected fault. Skipped with a notice when pytest is absent, or
#    when GRAPHDYN_SKIP_FAULTCHECK=1 (set by the tier-1 lint-gate test:
#    the same subset already runs in the suite proper — no double work).
if [ "${GRAPHDYN_SKIP_FAULTCHECK:-0}" = "1" ]; then
    echo "== faultcheck: GRAPHDYN_SKIP_FAULTCHECK=1 — SKIPPED (subset runs in tier-1) =="
elif python -c 'import pytest' 2>/dev/null; then
    echo "== faultcheck (pytest -m faultinject) =="
    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m faultinject \
        -p no:cacheprovider || fail=1
else
    echo "== faultcheck: pytest not installed — SKIPPED (pip install pytest to enable) =="
fi

# 4b. soakcheck — the bounded chaos-soak matrix standalone (python -m
#     graphdyn.resilience.soak --bounded): composed-fault kill/requeue
#     cycles over real CLI workloads — every scenario x seed must end in
#     bit-exact parity with a fault-free oracle, a schema-valid run
#     journal, and a parseable flight post-mortem per preemption. Skipped
#     with a notice when GRAPHDYN_SKIP_SOAKCHECK=1 (set by the tier-1
#     lint-gate test: the same bounded matrix runs in-suite via
#     tests/test_soak.py — no double work; mirrors faultcheck).
if [ "${GRAPHDYN_SKIP_SOAKCHECK:-0}" = "1" ]; then
    echo "== soakcheck: GRAPHDYN_SKIP_SOAKCHECK=1 — SKIPPED (matrix runs in tier-1) =="
else
    echo "== soakcheck (python -m graphdyn.resilience.soak --bounded) =="
    JAX_PLATFORMS=cpu python -m graphdyn.resilience.soak --bounded \
        --format=text || fail=1
fi

# 4c. servecheck — the job-service test subset standalone (pytest -m
#     serve): the durable spool state machine, byte-model admission,
#     bucketing, the worker's evict/requeue/quarantine ladder, and the
#     restarted-server recovery regression. Skipped with a notice when
#     pytest is absent, or when GRAPHDYN_SKIP_SERVECHECK=1 (set by the
#     tier-1 lint-gate test: the same subset already runs in the suite
#     proper — no double work; mirrors faultcheck).
if [ "${GRAPHDYN_SKIP_SERVECHECK:-0}" = "1" ]; then
    echo "== servecheck: GRAPHDYN_SKIP_SERVECHECK=1 — SKIPPED (subset runs in tier-1) =="
elif python -c 'import pytest' 2>/dev/null; then
    echo "== servecheck (pytest -m serve) =="
    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m serve \
        -p no:cacheprovider || fail=1
else
    echo "== servecheck: pytest not installed — SKIPPED (pip install pytest to enable) =="
fi

# 5. pallascheck — the interpret-mode Pallas kernel parity subset
#    standalone (pytest -m pallas_interpret): the fused BDCM kernel —
#    serial and grouped — must reproduce the XLA sweep within the
#    documented tolerance, grouped must equal G=1 bit-exactly, and the
#    fused one-kernel annealer (ops/pallas_anneal) must equal its XLA
#    twin bit-for-bit, on every PR, not only when a chip window happens
#    to run scripts/pallas_tpu_validate.py. Skipped with a notice when pytest is
#    absent, or when GRAPHDYN_SKIP_PALLASCHECK=1 (set by the tier-1
#    lint-gate test: the same subset already runs in the suite proper —
#    no double work; mirrors faultcheck).
if [ "${GRAPHDYN_SKIP_PALLASCHECK:-0}" = "1" ]; then
    echo "== pallascheck: GRAPHDYN_SKIP_PALLASCHECK=1 — SKIPPED (subset runs in tier-1) =="
elif python -c 'import pytest' 2>/dev/null; then
    echo "== pallascheck (pytest -m pallas_interpret) =="
    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m pallas_interpret \
        -p no:cacheprovider || fail=1
else
    echo "== pallascheck: pytest not installed — SKIPPED (pip install pytest to enable) =="
fi

# 6. hlocheck — the program-structure gate (graphdyn.analysis.graftcheck):
#    lower the headline entry points on the CPU backend, fingerprint the
#    compiled HLO, and diff against the committed ledger
#    (GRAFTCHECK_FINGERPRINTS.json) — a lost donation, a new op category,
#    a loop-structure change or a constant blowup fails here with a
#    pointed message, hardware-free. Then the graftcheck pytest subset
#    (pytest -m graftcheck: ledger parity, fingerprint invariance across
#    group extents, recompile guard). Skipped with a notice when
#    GRAPHDYN_SKIP_HLOCHECK=1 (set by the tier-1 lint-gate test: the
#    subset already runs in the suite proper — no double work; mirrors
#    faultcheck/pallascheck).
if [ "${GRAPHDYN_SKIP_HLOCHECK:-0}" = "1" ]; then
    echo "== hlocheck: GRAPHDYN_SKIP_HLOCHECK=1 — SKIPPED (subset runs in tier-1) =="
else
    echo "== hlocheck (graftcheck fingerprint ledger) =="
    # the simulated 8-device host platform matches the test harness, so the
    # multi-device entries (halo_rollout's 2-device ppermute program) are
    # CHECKED here rather than skipped as unsupported; APPEND to any
    # caller-provided XLA_FLAGS (mirroring tests/conftest.py) instead of
    # replacing them
    hlo_xla_flags="${XLA_FLAGS:-}"
    case "$hlo_xla_flags" in
        *xla_force_host_platform_device_count*) ;;
        *) hlo_xla_flags="$hlo_xla_flags --xla_force_host_platform_device_count=8" ;;
    esac
    JAX_PLATFORMS=cpu XLA_FLAGS="${hlo_xla_flags# }" \
        python -m graphdyn.analysis.graftcheck --format=text || fail=1
    if python -c 'import pytest' 2>/dev/null; then
        echo "== hlocheck (pytest -m graftcheck) =="
        JAX_PLATFORMS=cpu python -m pytest tests/ -q -m graftcheck \
            -p no:cacheprovider || fail=1
    else
        echo "== hlocheck: pytest not installed — graftcheck subset SKIPPED (pip install pytest to enable) =="
    fi
fi

# 6b. costcheck — the HLO-derived cost-model gate (graphdyn.analysis.
#     graftcost): re-derive every graftcheck-ledgered entry point's
#     byte/FLOP costs at the calibration shapes and diff them against the
#     committed COST_LEDGER.json (GB101 drift, GB102 stale hand models,
#     GB103 coverage, GB104 scaling-exponent departures). Then the
#     graftcost pytest subset (pytest -m graftcost: falsifiability both
#     ways, holdout scaling laws, the adapter/doc sync). Skipped with a
#     notice when GRAPHDYN_SKIP_COSTCHECK=1 (set by the tier-1 lint-gate
#     test: the subset already runs in that same suite; mirrors hlocheck).
if [ "${GRAPHDYN_SKIP_COSTCHECK:-0}" = "1" ]; then
    echo "== costcheck: GRAPHDYN_SKIP_COSTCHECK=1 — SKIPPED (subset runs in tier-1) =="
else
    echo "== costcheck (graftcost cost ledger) =="
    # same simulated 8-device host platform as hlocheck, so the
    # multi-device entries (halo_rollout) are checked, not skipped
    cost_xla_flags="${XLA_FLAGS:-}"
    case "$cost_xla_flags" in
        *xla_force_host_platform_device_count*) ;;
        *) cost_xla_flags="$cost_xla_flags --xla_force_host_platform_device_count=8" ;;
    esac
    JAX_PLATFORMS=cpu XLA_FLAGS="${cost_xla_flags# }" \
        python -m graphdyn.analysis.graftcost --format=text || fail=1
    if python -c 'import pytest' 2>/dev/null; then
        echo "== costcheck (pytest -m graftcost) =="
        JAX_PLATFORMS=cpu python -m pytest tests/ -q -m graftcost \
            -p no:cacheprovider || fail=1
    else
        echo "== costcheck: pytest not installed — graftcost subset SKIPPED (pip install pytest to enable) =="
    fi
fi

# 7. obscheck — the roofline-anchored runtime perf bands (python -m
#    graphdyn.obs check): measure the headline CPU proxies (packed
#    rollout, BDCM sweep core, entropy cell chunk) against rates derived
#    from ARCHITECTURE.md's byte model and a host bandwidth probe — an
#    order-of-magnitude runtime collapse fails here even with the HLO
#    fingerprint unchanged, hardware-free. Skipped with a notice when
#    GRAPHDYN_SKIP_OBSCHECK=1 (set by the tier-1 lint-gate test: the same
#    check runs in the suite proper via tests/test_obs.py — no double
#    work; mirrors faultcheck/pallascheck/hlocheck).
if [ "${GRAPHDYN_SKIP_OBSCHECK:-0}" = "1" ]; then
    echo "== obscheck: GRAPHDYN_SKIP_OBSCHECK=1 — SKIPPED (check runs in tier-1) =="
else
    echo "== obscheck (roofline perf bands, python -m graphdyn.obs check) =="
    JAX_PLATFORMS=cpu python -m graphdyn.obs check --format=text || fail=1
fi

# 8. memcheck — the device-memory bands (python -m graphdyn.obs memcheck):
#    measured peak bytes against the ARCHITECTURE.md byte models (packed
#    state, stacked-BDCM lattice incl. group-resident A, entropy chunk).
#    On this CPU container memory_stats is unavailable, so every row is an
#    explicit null + reason and the gate passes STRUCTURALLY — the bands
#    go live the first chip round that runs it. Skipped with a notice when
#    GRAPHDYN_SKIP_MEMCHECK=1 (set by the tier-1 lint-gate test: the same
#    check runs in the suite proper via tests/test_obs_device.py — no
#    double work; mirrors obscheck).
if [ "${GRAPHDYN_SKIP_MEMCHECK:-0}" = "1" ]; then
    echo "== memcheck: GRAPHDYN_SKIP_MEMCHECK=1 — SKIPPED (check runs in tier-1) =="
else
    echo "== memcheck (device-memory bands, python -m graphdyn.obs memcheck) =="
    JAX_PLATFORMS=cpu python -m graphdyn.obs memcheck --format=text || fail=1
fi

# 8b. colorcheck — the chromatic-kernel coloring contract (graphdyn.graphs
#     greedy_coloring): deterministic per seed, no monochromatic edge,
#     chi <= dmax+1, and the distance-2 construction proper on G^2 — an
#     invalid coloring would make the whole-independent-set device update
#     silently wrong, so the gate proves it host-side on RRG + ragged ER
#     samples. Skipped with a notice when GRAPHDYN_SKIP_COLORCHECK=1 (set
#     by the tier-1 lint-gate test: the same contract runs in-suite via
#     tests/test_graphs.py — no double work; mirrors obscheck).
if [ "${GRAPHDYN_SKIP_COLORCHECK:-0}" = "1" ]; then
    echo "== colorcheck: GRAPHDYN_SKIP_COLORCHECK=1 — SKIPPED (contract runs in tier-1) =="
else
    echo "== colorcheck (greedy-coloring validity, host numpy) =="
    JAX_PLATFORMS=cpu python - <<'PYEOF' || fail=1
import numpy as np
from graphdyn.graphs import (erdos_renyi_graph, greedy_coloring,
                             power_graph, random_regular_graph,
                             validate_coloring)
for name, g in (("rrg", random_regular_graph(512, 3, seed=0)),
                ("er", erdos_renyi_graph(400, 5.0 / 399, seed=1))):
    c = greedy_coloring(g, seed=0)
    problems = validate_coloring(g, c)
    assert problems == [], (name, problems)
    assert np.array_equal(c, greedy_coloring(g, seed=0)), \
        f"{name}: coloring not deterministic per seed"
    g2 = power_graph(g, 2)
    c2 = greedy_coloring(g2, seed=0)
    problems2 = validate_coloring(g2, c2)
    assert problems2 == [], (name, problems2)
    print(f"colorcheck: {name} chi={int(c.max()) + 1} (dmax={g.dmax}) "
          f"chi2={int(c2.max()) + 1} (dmax2={g2.dmax}) OK")
PYEOF
fi

# 8c. racecheck — the graftrace host-concurrency auditor
#     (graphdyn.analysis.racecheck): the static AST pass inventories the
#     thread/lock/shared-global surface, enforces GT001-GT005 and diffs
#     the declarations against the committed CONCURRENCY_LEDGER.json —
#     undeclared concurrency growth or a lock-order hazard fails here,
#     hardware-free and jax-free. Then the racecheck pytest subset
#     (pytest -m racecheck: rule catalogue, runtime lock proxy, the
#     GRAPHDYN_RACECHECK=1 smoke). Skipped with a notice when
#     GRAPHDYN_SKIP_RACECHECK=1 (set by the tier-1 lint-gate test: the
#     subset already runs in the suite proper — no double work; mirrors
#     hlocheck).
if [ "${GRAPHDYN_SKIP_RACECHECK:-0}" = "1" ]; then
    echo "== racecheck: GRAPHDYN_SKIP_RACECHECK=1 — SKIPPED (subset runs in tier-1) =="
else
    echo "== racecheck (graftrace concurrency ledger) =="
    python -m graphdyn.analysis.racecheck --format=text || fail=1
    if python -c 'import pytest' 2>/dev/null; then
        echo "== racecheck (pytest -m racecheck) =="
        JAX_PLATFORMS=cpu python -m pytest tests/ -q -m racecheck \
            -p no:cacheprovider || fail=1
    else
        echo "== racecheck: pytest not installed — racecheck subset SKIPPED (pip install pytest to enable) =="
    fi
fi

# 9. benchcheck — the benchmark's single-JSON-line contract, live (python
#    bench.py --smoke on the CPU backend): one line of JSON, a positive
#    headline value, and a positive ensemble_rate row (the grouped-driver
#    throughput the pipeline ships). A formatting regression here silently
#    voids a whole round's benchmark artifact. Skipped with a notice when
#    GRAPHDYN_SKIP_BENCHCHECK=1 (set by the tier-1 lint-gate test — the
#    contract already runs in-suite via tests/test_bench_contract.py).
if [ "${GRAPHDYN_SKIP_BENCHCHECK:-0}" = "1" ]; then
    echo "== benchcheck: GRAPHDYN_SKIP_BENCHCHECK=1 — SKIPPED (contract runs in tier-1) =="
else
    echo "== benchcheck (python bench.py --smoke) =="
    GRAPHDYN_FORCE_PLATFORM="${GRAPHDYN_FORCE_PLATFORM:-cpu}" JAX_PLATFORMS=cpu \
        python bench.py --smoke > /tmp/graphdyn_benchcheck.json || fail=1
    python - /tmp/graphdyn_benchcheck.json <<'PYEOF' || fail=1
import json, sys
lines = [ln for ln in open(sys.argv[1]) if ln.strip()]
assert len(lines) == 1, f"stdout must be ONE JSON line, got {len(lines)}"
row = json.loads(lines[0])
assert row.get("value", 0) > 0, f"headline value must be > 0: {row.get('value')}"
assert row.get("unit") == "spin-updates/s", row.get("unit")
assert row.get("ensemble_rate", 0) > 0, \
    f"ensemble_rate row must be > 0: {row.get('ensemble_rate')}"
# the entropy cell-ladder row: a measured positive rate, or an explicit
# null + reason — NEVER 0.0 (a skip must be unmistakable from a collapse)
assert "entropy_cell_rate" in row, "entropy_cell_rate row absent"
ecr = row["entropy_cell_rate"]
if ecr is None:
    assert row.get("entropy_cell_rate_skipped_reason"), \
        "null entropy_cell_rate needs entropy_cell_rate_skipped_reason"
else:
    assert ecr > 0, f"entropy_cell_rate must be > 0 or null+reason: {ecr}"
# the grouped-Pallas A/B column (chip-only): same null-or-positive contract
assert "entropy_cell_rate_pallas" in row, "entropy_cell_rate_pallas absent"
ecp = row["entropy_cell_rate_pallas"]
if ecp is None:
    assert row.get("entropy_cell_rate_pallas_skipped_reason"), \
        "null entropy_cell_rate_pallas needs a skipped_reason"
else:
    assert ecp > 0, f"entropy_cell_rate_pallas must be > 0 or null+reason: {ecp}"
# the graftcost derived-cost columns: the committed ledger models
# evaluated at the bench size — positive, or an explicit null + reason
# (e.g. a backend the ledger was never blessed on) — NEVER 0.0
for col in ("derived_bytes", "arithmetic_intensity"):
    assert col in row, f"{col} column absent"
    v = row[col]
    if v is None:
        assert row.get(f"{col}_skipped_reason"), \
            f"null {col} needs {col}_skipped_reason"
    else:
        assert v > 0, f"{col} must be > 0 or null+reason: {v}"
# the power-law bucketed-layout row: a measured positive rate with its
# equal-edge RRG control, or an explicit null + reason — NEVER 0.0
assert "powerlaw_rate" in row, "powerlaw_rate row absent"
plr = row["powerlaw_rate"]
if plr is None:
    assert row.get("powerlaw_rate_skipped_reason"), \
        "null powerlaw_rate needs powerlaw_rate_skipped_reason"
else:
    assert plr > 0, f"powerlaw_rate must be > 0 or null+reason: {plr}"
    det = row["powerlaw_rate_detail"]
    assert det["rrg_padded_rate"] > 0 and det["rrg_over_bucketed_x"] > 0
    assert det["hub_degree"] > 0 and det["table_entries"] > 0
# the out-of-core streamed rows: the overlapped chunk-gather rate on an
# adjacency exceeding the clamped device budget, and the live edge-churn
# rate with the rollout still advancing — measured positive with the
# forced-synchronous A/B detail, or an explicit null + reason — NEVER 0.0
assert "stream_rate" in row, "stream_rate row absent"
str_r = row["stream_rate"]
if str_r is None:
    assert row.get("stream_rate_skipped_reason"), \
        "null stream_rate needs stream_rate_skipped_reason"
else:
    assert str_r > 0, f"stream_rate must be > 0 or null+reason: {str_r}"
    det = row["stream_rate_detail"]
    assert det["sync_rate"] > 0 and det["chunks"] >= 2, det
    assert det["device_budget_bytes"] < det["resident_model_bytes"], det
assert "churn_rate" in row, "churn_rate row absent"
chr_r = row["churn_rate"]
if chr_r is None:
    assert row.get("churn_rate_skipped_reason"), \
        "null churn_rate needs churn_rate_skipped_reason"
else:
    assert chr_r > 0, f"churn_rate must be > 0 or null+reason: {chr_r}"
    det = row["churn_rate_detail"]
    assert det["applied_mutations"] > 0 and det["spin_update_rate"] > 0, det
# the sharded streamed rows (PR 20): the composed chunk-walk x exchange
# engine's weak-scaling efficiency and the churn-driven live-repartition
# drive — measured positive, or an explicit null + reason — NEVER 0.0
assert "stream_shard_efficiency" in row, "stream_shard_efficiency absent"
sse = row["stream_shard_efficiency"]
if sse is None:
    assert row.get("stream_shard_efficiency_skipped_reason"), \
        "null stream_shard_efficiency needs its skipped_reason"
    print("benchcheck: stream_shard_efficiency skipped:",
          row["stream_shard_efficiency_skipped_reason"])
else:
    assert sse > 0, f"stream_shard_efficiency > 0 or null+reason: {sse}"
    assert row.get("stream_shard_rate_by_shards", {}).get("1", 0) > 0, \
        "measured stream_shard row needs a positive P=1 rate"
assert "churn_repartition_rate" in row, "churn_repartition_rate absent"
crr = row["churn_repartition_rate"]
if crr is None:
    assert row.get("churn_repartition_rate_skipped_reason"), \
        "null churn_repartition_rate needs its skipped_reason"
    print("benchcheck: churn_repartition_rate skipped:",
          row["churn_repartition_rate_skipped_reason"])
else:
    assert crr > 0, \
        f"churn_repartition_rate must be > 0 or null+reason: {crr}"
    det = row["churn_repartition_rate_detail"]
    assert det["applied_mutations"] > 0 and det["spin_update_rate"] > 0, det
# the serve rows: multi-tenant bucket hit rate and end-to-end job
# latency through the real worker — measured positive, or an explicit
# null + reason — NEVER 0.0 (the same null-or-positive contract)
assert "serve_bucket_hit_rate" in row, "serve_bucket_hit_rate row absent"
sbh = row["serve_bucket_hit_rate"]
if sbh is None:
    assert row.get("serve_bucket_hit_rate_skipped_reason"), \
        "null serve_bucket_hit_rate needs a skipped_reason"
else:
    assert sbh["hit_rate"] > 0, f"serve bucket hit_rate must be > 0: {sbh}"
    assert sbh["jobs"] > 0 and sbh["misses"] > 0, sbh
assert "serve_job_latency" in row, "serve_job_latency row absent"
sjl = row["serve_job_latency"]
if sjl is None:
    assert row.get("serve_job_latency_skipped_reason"), \
        "null serve_job_latency needs a skipped_reason"
else:
    assert sjl["warm_p50_s"] > 0 and sjl["cold_p50_s"] > 0, sjl
    assert sjl["warm_p99_s"] > 0 and sjl["cold_p99_s"] > 0, sjl
    assert sjl["cold_over_warm_p50_x"] > 0 and sjl["jobs"] > 0, sjl
# the graftcheck fingerprint summary: a structural snapshot per round, or
# an explicit null + reason — never silently absent
assert "fingerprints" in row, "fingerprints row absent"
fp = row["fingerprints"]
if fp is None:
    assert row.get("fingerprints_skipped_reason"), \
        "null fingerprints needs fingerprints_skipped_reason"
    print("benchcheck: fingerprints skipped:",
          row["fingerprints_skipped_reason"])
else:
    assert fp.get("entries"), "fingerprints row carries no entries"
    # round-over-round structural diff: compare against the most recent
    # BENCH_r*.json that persisted a same-backend fingerprint row (older
    # rounds predate the column — skipped with a notice, not silently)
    import glob
    from graphdyn.analysis.graftcheck import diff_bench_fingerprints
    prev_rows = []
    for p in sorted(glob.glob("BENCH_r*.json")):
        try:
            with open(p) as fh:
                r = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        if (r.get("fingerprints") or {}).get("backend") == fp["backend"]:
            prev_rows.append((p, r["fingerprints"]))
    if not prev_rows:
        print("benchcheck: no previous round carries a fingerprint row for "
              f"backend={fp['backend']} — structural diff starts next round")
    else:
        path, prev = prev_rows[-1]
        drift = diff_bench_fingerprints(prev, fp)
        if drift:
            # round artifacts are immutable history: a DELIBERATE change
            # is blessed by matching the committed ledger
            # (--update-ledger), and the baseline refreshes next round
            from graphdyn.analysis.graftcheck import bench_drift_blessed
            if bench_drift_blessed(fp):
                print(f"benchcheck: fingerprint drift vs {path} is "
                      "LEDGER-BLESSED (row matches the committed "
                      "GRAFTCHECK_FINGERPRINTS.json) — baseline refreshes "
                      "when the next round persists its row")
            else:
                for f in drift:
                    print(f"benchcheck: FINGERPRINT DRIFT vs {path}: "
                          f"{f.entry}: {f.code} {f.message}")
                raise AssertionError(
                    f"{len(drift)} structural drift finding(s) vs {path} "
                    "not blessed by the ledger"
                )
        else:
            print(f"benchcheck: fingerprints stable vs {path} "
                  f"({len(fp['entries'])} entries)")
# the halo weak-scaling column (node-axis sharding): a measured efficiency
# rate(P)/(P*rate(1)), or an explicit null + reason (fewer than 2 devices)
# — NEVER 0.0; same null-or-positive contract as ensemble_rate
assert "halo_weak_efficiency" in row, "halo_weak_efficiency column absent"
hwe = row["halo_weak_efficiency"]
if hwe is None:
    assert row.get("halo_weak_efficiency_skipped_reason"), \
        "null halo_weak_efficiency needs halo_weak_efficiency_skipped_reason"
    print("benchcheck: halo_weak_efficiency skipped:",
          row["halo_weak_efficiency_skipped_reason"])
else:
    assert hwe > 0, f"halo_weak_efficiency must be > 0 or null+reason: {hwe}"
    assert row.get("halo_rate_by_shards", {}).get("1", 0) > 0, \
        "measured halo row needs a positive P=1 rate"
# the exchange-traffic column rides with it: 4*W*sum(ghosts) of the
# measured partition, or null + the same reason
assert "halo_bytes_per_step" in row, "halo_bytes_per_step column absent"
hbs = row["halo_bytes_per_step"]
if hbs is None:
    assert row.get("halo_bytes_per_step_skipped_reason"), \
        "null halo_bytes_per_step needs halo_bytes_per_step_skipped_reason"
else:
    assert hbs > 0, f"halo_bytes_per_step must be > 0 or null+reason: {hbs}"
# the time-to-target search rows (tta_tempering / tta_chromatic): a
# measured speedup over the serial SA chain, or an explicit null + reason
# — NEVER 0.0; a measured tempering row additionally needs a NONZERO
# swap_acceptance_rate (a dead ladder — 0% swaps — must fail loudly
# instead of benching as "fast")
for key in ("tta_tempering", "tta_chromatic"):
    assert key in row, f"{key} row absent"
    v = row[key]
    if v is None:
        assert row.get(key + "_skipped_reason"), \
            f"null {key} needs {key}_skipped_reason"
        print(f"benchcheck: {key} skipped:", row[key + "_skipped_reason"])
    else:
        assert v.get("speedup_x", 0) > 0, (key, v)
        assert v.get("device_steps", 0) > 0, (key, v)
assert "swap_acceptance_rate" in row, "swap_acceptance_rate column absent"
if row["tta_tempering"] is not None:
    assert (row["swap_acceptance_rate"] or 0) > 0, \
        "measured tta_tempering with a DEAD ladder (swap_acceptance_rate " \
        f"= {row['swap_acceptance_rate']}) — swaps never accepted"
# the fused one-kernel annealer rows: tta_fused (device-step A/B, runs on
# CPU — counts are seed-deterministic) and fused_sa_rate (chip-only
# throughput) — both null-or-positive, never 0.0
assert "tta_fused" in row, "tta_fused row absent"
tf = row["tta_fused"]
if tf is None:
    assert row.get("tta_fused_skipped_reason"), \
        "null tta_fused needs tta_fused_skipped_reason"
    print("benchcheck: tta_fused skipped:", row["tta_fused_skipped_reason"])
else:
    assert tf.get("speedup_x", 0) > 0, tf
    assert tf.get("device_steps", 0) > 0, tf
    assert tf.get("kernel") in ("xla", "pallas", "pallas-interpret"), tf
assert "fused_sa_rate" in row, "fused_sa_rate column absent"
fsr = row["fused_sa_rate"]
if fsr is None:
    assert row.get("fused_sa_rate_skipped_reason"), \
        "null fused_sa_rate needs fused_sa_rate_skipped_reason"
    print("benchcheck: fused_sa_rate skipped:",
          row["fused_sa_rate_skipped_reason"])
else:
    assert fsr > 0, f"fused_sa_rate must be > 0 or null+reason: {fsr}"
# the rider A/B (saved per-chunk sync on a fixed-budget ladder) rides in
# the tta row whenever the tta legs measured
if row["tta_tempering"] is not None:
    sab = row.get("tta_fixed_budget_sync")
    assert sab and sab.get("sync_s", 0) > 0 and sab.get("nosync_s", 0) > 0, \
        f"measured tta row without a valid tta_fixed_budget_sync A/B: {sab}"
# the durable-store save-overhead column: an interleaved p50/p99 A/B of
# DurableCheckpoint.save vs raw Checkpoint.save, or an explicit null +
# reason — never silently absent
assert "ckpt_save_overhead" in row, "ckpt_save_overhead column absent"
cso = row["ckpt_save_overhead"]
if cso is None:
    assert row.get("ckpt_save_overhead_skipped_reason"), \
        "null ckpt_save_overhead needs ckpt_save_overhead_skipped_reason"
    print("benchcheck: ckpt_save_overhead skipped:",
          row["ckpt_save_overhead_skipped_reason"])
else:
    assert cso.get("overhead_p50_x", 0) > 0, cso
    assert cso.get("raw_p50_s", 0) > 0 and cso.get("durable_p50_s", 0) > 0
    assert cso.get("snapshot_bytes", 0) > 0
# the liveness-tax column: an interleaved watchdog-on/off A/B of the
# entropy smoke workload, or an explicit null + reason — never silently
# absent; beats_per_run > 0 proves the workload actually heartbeats
assert "heartbeat_overhead" in row, "heartbeat_overhead column absent"
hbo = row["heartbeat_overhead"]
if hbo is None:
    assert row.get("heartbeat_overhead_skipped_reason"), \
        "null heartbeat_overhead needs heartbeat_overhead_skipped_reason"
    print("benchcheck: heartbeat_overhead skipped:",
          row["heartbeat_overhead_skipped_reason"])
else:
    assert hbo.get("overhead_p50_x", 0) > 0, hbo
    assert hbo.get("off_p50_s", 0) > 0 and hbo.get("on_p50_s", 0) > 0, hbo
    assert hbo.get("beats_per_run", 0) > 0, hbo
# the device-memory column: a positive peak, or an explicit null + reason
# (CPU: no usable memory_stats) — never silently absent, never 0
assert "peak_hbm_bytes" in row, "peak_hbm_bytes column absent"
if row["peak_hbm_bytes"] is None:
    assert row.get("peak_hbm_bytes_skipped_reason"), \
        "null peak_hbm_bytes needs peak_hbm_bytes_skipped_reason"
else:
    assert row["peak_hbm_bytes"] > 0, row["peak_hbm_bytes"]
# the obs ledger columns: a path + manifest hash, or an explicit null +
# reason — never silently absent
assert "obs_ledger" in row, "obs_ledger column absent"
if row["obs_ledger"] is None:
    assert row.get("obs_ledger_skipped_reason"), \
        "null obs_ledger needs obs_ledger_skipped_reason"
else:
    assert row.get("obs_manifest_sha"), "obs_ledger without obs_manifest_sha"
# the cross-round RATE trend gate (graphdyn.obs.trend) must have RUN or
# been explicitly skipped — and unblessed drift fails the gate here
assert "obs_trend_status" in row, "trend gate did not run (no status)"
status = row["obs_trend_status"]
if status in (None, "skipped"):
    assert row.get("obs_trend_skipped_reason"), \
        f"trend status {status!r} needs obs_trend_skipped_reason"
    print("benchcheck: trend gate skipped:", row["obs_trend_skipped_reason"])
elif status == "drift":
    for f in row.get("obs_trend_findings", []):
        print(f"benchcheck: RATE DRIFT: {f['row']}: {f['code']} "
              f"{f['message']}")
    raise AssertionError(
        "unblessed rate drift vs the previous comparable round — if "
        "deliberate, bless with: python -m graphdyn.obs trend <row.json> "
        "--bless"
    )
else:
    assert status in ("stable", "blessed", "no_baseline"), status
    print(f"benchcheck: trend gate {status}")
print(f"benchcheck: value={row['value']:.3e} "
      f"ensemble_rate={row['ensemble_rate']:.3e} "
      f"ensemble_speedup={row.get('ensemble_speedup', 0):.2f}x "
      f"entropy_cell_rate={row['entropy_cell_rate']}")
PYEOF
fi

if [ "$fail" -ne 0 ]; then
    echo "lint gate: FAILED" >&2
    exit 1
fi
echo "lint gate: OK"
