#!/usr/bin/env python3
"""Fold a completed tpu_bench_session output directory into the round's
benchmark artifacts.

Usage: python scripts/collect_tpu_session.py SESSION_DIR [BENCH_CONFIGS_JSON]

- Parses ``bench_headline.json`` (one JSON line) and the per-config JSON
  lines inside ``configs_tpu.json``.
- Merges them into the round's BENCH_CONFIGS artifact under a
  ``tpu_full`` key (keeping the existing cpu_smoke section), with the
  session's gather/probe JSONL files summarized alongside.
- Prints a one-screen summary for the commit message.
"""

import importlib.util
import json
import os
import sys

# One JSON-lines parser shared with the aggregator (both scripts must agree
# on which stdout lines count as metrics); loaded by path because scripts/
# is not a package and this tool stays stdlib-pure otherwise.
_spec = importlib.util.spec_from_file_location(
    "run_baseline_configs",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "run_baseline_configs.py"))
_rbc = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_rbc)
json_lines = _rbc.json_lines

# One shared backend allowlist for BOTH the headline and the configs guards
# (ADVICE r04: the two guards had drifted apart). 'axon' is the tunneled-TPU
# plugin; jax reports its backend as 'tpu', but configs docs written by the
# aggregator may record either name. Unknown/missing metadata is a soft note,
# never the fallback warning — a failed probe is not evidence of a fallback.
CHIP_BACKENDS = ("tpu", "axon")
UNKNOWN_BACKENDS = (None, "unknown")


def _write_json_atomic(path, doc):
    """Temp-file + os.replace JSON write (the graphdyn.utils.io discipline,
    inlined because this tool stays stdlib-pure): a preemption mid-write
    leaves the old artifact intact, never a torn one."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


def read_json_lines(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return json_lines(f.read())


def main(session_dir, bench_configs="BENCH_CONFIGS_r05.json"):
    session_dir = os.path.normpath(session_dir)
    out = {}

    head = read_json_lines(os.path.join(session_dir, "bench_headline.json"))
    if head:
        out["headline"] = head[-1]
        backend = out["headline"].get("backend")
        if backend in UNKNOWN_BACKENDS:
            # metadata missing/probe failed: not a fallback, but say so
            out["headline_note"] = "headline backend unknown (no metadata)"
        elif backend not in CHIP_BACKENDS:
            # a wedged-relay CPU fallback must not masquerade as chip data
            out["warning"] = (
                f"headline backend is {backend!r}, not the chip — the "
                "session ran on a fallback backend; rates are NOT chip "
                "numbers"
            )

    cfg_path = os.path.join(session_dir, "configs_tpu.json")
    if os.path.exists(cfg_path):
        try:
            with open(cfg_path) as f:
                out["configs"] = json.load(f)
            if isinstance(out["configs"], dict):
                cfg_backend = out["configs"].get("backend")
                if cfg_backend in UNKNOWN_BACKENDS:
                    # metadata probe failed — keep that visible without the
                    # fallback warning (the rates may well be chip numbers)
                    out["configs_note"] = ("configs backend unknown "
                                           "(metadata probe failed)")
                elif cfg_backend not in CHIP_BACKENDS:
                    # same guard as the headline: a fallback backend's config
                    # rates must not merge into the round doc as chip numbers
                    out["configs_warning"] = (
                        f"configs backend is {cfg_backend!r}, not the chip — "
                        "rates are NOT chip numbers"
                    )
        except json.JSONDecodeError as e:
            # a killed aggregator leaves an empty/truncated file; the
            # no-usable-artifacts guard below must still get to run
            out["configs_error"] = f"unparseable configs_tpu.json: {e}"

    for name in ("gather_experiment", "pallas_gather_probe"):
        rows = read_json_lines(os.path.join(session_dir, f"{name}.jsonl"))
        if rows:
            out[name] = rows

    pv_path = os.path.join(session_dir, "PALLAS_TPU.json")
    if os.path.exists(pv_path):
        try:
            with open(pv_path) as f:
                pv = json.load(f)
            out["pallas_validate"] = {
                "packed_equivalence": pv.get("packed_equivalence"),
                "backend": pv.get("info", {}).get("backend"),
            }
        except json.JSONDecodeError as e:
            out["pallas_validate_error"] = str(e)

    phys_path = os.path.join(session_dir, "physics_tpu.json")
    if os.path.exists(phys_path):
        try:
            with open(phys_path) as f:
                out["physics"] = json.load(f)
        except json.JSONDecodeError as e:
            # a killed physics stage leaves a partial file; keep merging the
            # other artifacts (same tolerance as read_json_lines)
            out["physics_error"] = f"unparseable physics_tpu.json: {e}"

    cons_path = os.path.join(session_dir, "consensus_tpu.json")
    if os.path.exists(cons_path):
        try:
            with open(cons_path) as f:
                out["consensus_physics"] = json.load(f)
            # same non-dict tolerance as the configs block: a truncated/
            # rewritten file can parse as a list or string
            cons_backend = (out["consensus_physics"].get("backend")
                            if isinstance(out["consensus_physics"], dict)
                            else None)
            if cons_backend in UNKNOWN_BACKENDS:
                out["consensus_physics_note"] = (
                    "consensus backend unknown (no metadata)")
            elif cons_backend not in CHIP_BACKENDS:
                # same guard as headline/configs: fallback data stays
                # labeled (consensus *physics* is backend-independent, but
                # the chip-evidence claim is not)
                out["consensus_physics_warning"] = (
                    f"consensus backend is {cons_backend!r}, not the chip")
        except json.JSONDecodeError as e:
            out["consensus_physics_error"] = (
                f"unparseable consensus_tpu.json: {e}")

    cfgs_present = out.get("configs")
    if isinstance(cfgs_present, dict):
        # the aggregator writes a valid-but-empty doc at startup; an empty
        # configs list is NOT a usable artifact for the guard below
        cfgs_present = cfgs_present.get("configs")
    if not out.get("headline") and not cfgs_present:
        # a wedged session leaves empty files: refuse to stamp the round doc
        # as 'captured' over nothing (the fallback warning can only fire when
        # a headline row exists at all)
        print(f"no usable artifacts in {session_dir}; round doc left unchanged")
        return 1

    doc = {}
    if os.path.exists(bench_configs):
        with open(bench_configs) as f:
            doc = json.load(f)
    doc["tpu_full"] = out
    stamp = "tpu_full captured from " + os.path.basename(session_dir)
    if stamp not in doc.get("status", ""):          # reruns stay idempotent
        doc["status"] = doc.get("status", "") + " | " + stamp
    _write_json_atomic(bench_configs, doc)

    print(f"merged into {bench_configs}:")
    if "headline" in out:
        h = out["headline"]
        v = h.get("value")
        v = f"{v:.3e}" if isinstance(v, (int, float)) else repr(v)
        print(f"  headline: {v} {h.get('unit')} backend={h.get('backend')} "
              f"(roofline_fraction={h.get('roofline_fraction_v5e')}"
              f"{', ERROR: ' + str(h['error']) if 'error' in h else ''})")
    if "warning" in out:
        print(f"  WARNING: {out['warning']}")
    for row in out.get("pallas_gather_probe", []):
        print(f"  probe: {row}")
    cons = out.get("consensus_physics")
    if isinstance(cons, dict):
        pts = [(r.get("m0"), r.get("consensus_fraction"))
               for r in cons.get("rows", [])]
        print(f"  consensus physics: backend={cons.get('backend')} "
              f"{len(pts)} m0 points {pts[:4]}...")
    cfgs = out.get("configs")
    if isinstance(cfgs, dict):
        cfgs = cfgs.get("configs", [])
    for c in cfgs or []:
        if isinstance(c, dict):
            print(f"  {c.get('config')}: rc={c.get('rc')} "
                  f"metrics={len(c.get('metrics', []))}")
    return 0


if __name__ == "__main__":
    if not 2 <= len(sys.argv) <= 3:
        print(__doc__.strip().splitlines()[2])   # the Usage line
        sys.exit(2)
    sys.exit(main(*sys.argv[1:]))
