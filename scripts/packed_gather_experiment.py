"""Measure packed-kernel gather formulations on the real chip.

Question (ARCHITECTURE.md roofline): the round-2 packed kernel's single big
gather produces a ``[n, dmax, W]`` intermediate. If XLA materializes it in
HBM, per-step traffic is ~5 GB instead of the 2 GB streaming minimum at
n=1e6, W=128, d=3. Variants measured here, all through the library kernel
(`graphdyn.ops.packed.packed_rollout`, whose two gather schedules are
bit-identity-tested in tests/test_packed.py):

  A. fused        — one gather materializing [n, dmax, W] before the CSA
                    (gather="fused", the round-2 formulation).
  B. per_slot     — dmax separate [n, W] gathers, each fused into the CSA
                    accumulation (gather="per_slot", the default).
  C. per_slot + column-sorted neighbor slots — same kernel, nbr sorted
                    ascending within each row (the CSA sum is
                    slot-order-invariant, so results are unchanged).

All variants run on the BFS-reordered graph (the round-3 locality win).
Usage: python scripts/packed_gather_experiment.py [--n 1000000] [--w 128]
Prints one JSON line per variant.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import benchmarks.common  # noqa: F401 — applies GRAPHDYN_FORCE_PLATFORM

import numpy as np

import jax.numpy as jnp


def _sync(x):
    from benchmarks.common import _sync as fence

    fence(x)


def time_chained(step, state0, updates_per_call, iters=3):
    """Shared timing harness: warmup call, then ``iters`` chained calls
    (each consumes the previous output) fenced by a device-to-host read.
    Returns updates/sec."""
    out = step(state0)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(out)
    _sync(out)
    return updates_per_call * iters / (time.perf_counter() - t0)


def time_rollout(nbr, deg, sp, steps, gather, iters=3):
    from graphdyn.ops.packed import packed_rollout

    n, W = sp.shape
    return time_chained(
        lambda x: packed_rollout(nbr, deg, x, steps, gather=gather),
        sp, n * W * 32 * steps, iters=iters,
    )


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--w", type=int, default=128)
    ap.add_argument("--d", type=int, default=3)
    ap.add_argument("--steps", type=int, default=10)
    args = ap.parse_args()

    from graphdyn.graphs import bfs_order, permute_nodes, random_regular_graph

    g = random_regular_graph(args.n, args.d, seed=1)
    g, _ = permute_nodes(g, bfs_order(g))
    from benchmarks.common import draw_u32

    nbr = jnp.asarray(g.nbr)
    deg = jnp.asarray(g.deg)
    nbr_sorted = jnp.asarray(np.sort(g.nbr, axis=1))
    sp = draw_u32(0, (args.n, args.w))

    for name, gather, tbl in [
        ("A_fused_gather", "fused", nbr),
        ("B_per_slot", "per_slot", nbr),
        ("C_per_slot_sorted", "per_slot", nbr_sorted),
    ]:
        rate = time_rollout(tbl, deg, sp, args.steps, gather)
        print(
            json.dumps(
                {
                    "variant": name,
                    "spin_updates_per_sec": rate,
                    "n": args.n,
                    "W": args.w,
                    "d": args.d,
                }
            ),
            flush=True,
        )

    # D: full Pallas dynamics step with explicitly pipelined per-row DMAs
    # (graphdyn.ops.pallas_packed — the gather probe's pattern graduated
    # into the kernel). Chip-only: interpret mode is not a rate.
    import jax

    if jax.default_backend() == "tpu":
        from graphdyn.ops.pallas_packed import (
            pallas_packed_rollout,
            pallas_packed_rollout_general,
        )

        variants = [
            ("D_pallas_row_dma",
             lambda x, dp: pallas_packed_rollout(
                 nbr, g.deg, x, args.steps, depth=dp)),
            # E: the general-degree kernel on the same uniform graph — its
            # overhead vs D (SMEM threshold reads + own-row block) is the
            # cost of ragged/even-degree support
            ("E_pallas_general",
             lambda x, dp: pallas_packed_rollout_general(
                 nbr, jnp.asarray(g.deg), x, args.steps, depth=dp)),
        ]
        for name, fn in variants:
            for depth in (8, 16):
                try:
                    rate = time_chained(
                        lambda x, f=fn, dp=depth: f(x, dp),
                        sp, args.n * args.w * 32 * args.steps,
                    )
                    print(json.dumps({
                        "variant": name, "depth": depth,
                        "spin_updates_per_sec": rate,
                        "n": args.n, "W": args.w, "d": args.d,
                    }), flush=True)
                except Exception as e:  # noqa: BLE001 — record, keep going
                    print(json.dumps({
                        "variant": name, "depth": depth,
                        "error": str(e)[:300],
                    }), flush=True)

    # int8 kernel A/B (the SA solver's hot rollout — ops.dynamics)
    from graphdyn.ops.dynamics import batched_rollout

    from benchmarks.common import draw_pm1_int8

    R8 = 64
    s8 = draw_pm1_int8(1, (R8, args.n))
    for name, gather in [("int8_A_fused", "fused"), ("int8_B_per_slot", "per_slot")]:
        rate = time_chained(
            lambda x, g=gather: batched_rollout(nbr, x, args.steps, gather=g),
            s8, args.n * R8 * args.steps,
        )
        print(
            json.dumps(
                {"variant": name, "spin_updates_per_sec": rate,
                 "n": args.n, "R": R8, "d": args.d}
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
