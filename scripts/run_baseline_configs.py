"""Run all five BASELINE benchmark configs and aggregate into one JSON doc.

Each config runs as its own subprocess (fresh JAX runtime — no HBM carryover
between configs; one config crashing cannot take down the rest). The JSON
lines every config prints via ``benchmarks.common.report`` are collected
into a single artifact.

Wedge resilience (the TPU relay drops unpredictably mid-session):
- the output doc is rewritten after every config, so an outer timeout
  killing the aggregator keeps everything that completed;
- a re-run against the same --out resumes: configs already present with
  rc=0 and metrics are kept as-is and skipped;
- device metadata comes from a timeout-bounded subprocess *after* the
  configs (metadata must never spend chip-window time before config 1,
  nor hang the aggregator when the relay is wedged).

Usage:
  python scripts/run_baseline_configs.py --out BENCH_CONFIGS_r03.json [--full]
  # CPU smoke:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/run_baseline_configs.py --out smoke.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

CONFIGS = [
    "config1_sa_rrg",
    "config2_hpr",
    "config3_er_majority",
    "config4_bdcm_entropy",
    "config5_multichip_sa",
]

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def json_lines(text: str) -> list[dict]:
    """Every parseable JSON-object line in ``text`` (non-JSON lines skipped)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return out


def run_config(name: str, full: bool, timeout_s: float) -> dict:
    # Platform forcing reaches the subprocess via inherited env: main() sets
    # GRAPHDYN_FORCE_PLATFORM in os.environ before the first call, and
    # benchmarks.common applies it before first jax use (survives plugins
    # that pin jax_platforms at interpreter startup).
    cmd = [sys.executable, os.path.join(ROOT, "benchmarks", f"{name}.py")]
    if full:
        cmd.append("--full")
    t0 = time.time()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s, cwd=ROOT,
        )
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc, out, err = -1, (e.stdout or ""), f"TIMEOUT after {timeout_s}s"
    metrics = json_lines(out)
    entry = {
        "config": name,
        "rc": rc,
        "elapsed_s": round(time.time() - t0, 1),
        "metrics": metrics,
    }
    if rc != 0 or not metrics:
        entry["stderr_tail"] = "\n".join(err.splitlines()[-15:])
    return entry


def probe_device_info(timeout_s: float = 180.0) -> tuple[str, list[str]]:
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import benchmarks.common, jax, json;"
             "print(json.dumps({'backend': jax.default_backend(),"
             " 'devices': [str(d) for d in jax.devices()]}))"],
            capture_output=True, text=True, timeout=timeout_s, cwd=ROOT,
        )
    except subprocess.TimeoutExpired:
        return "unknown", []
    for info in json_lines(probe.stdout):
        if "backend" in info and "devices" in info:
            return info["backend"], info["devices"]
    return "unknown", []


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="BENCH_CONFIGS.json")
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--timeout", type=float, default=3600.0, help="per-config seconds")
    ap.add_argument("--only", nargs="*", help="subset of config names")
    ap.add_argument(
        "--platform", choices=["cpu", "tpu", "axon"], default=None,
        help="force the JAX platform in each config subprocess ('axon' is "
        "the tunneled-TPU plugin name: chip-or-hang, never a silent CPU "
        "fallback; 'tpu' means a locally attached chip)",
    )
    ap.add_argument(
        "--fresh", action="store_true",
        help="ignore completed configs in an existing --out file (default: resume)",
    )
    args = ap.parse_args()

    if args.platform:
        os.environ["GRAPHDYN_FORCE_PLATFORM"] = args.platform

    mode = "full" if args.full else "smoke"
    # What actually selects the backend in every subprocess — resumed
    # results are only comparable when ALL of these match the prior run's
    # (JAX_PLATFORMS matters too: the documented CPU smoke uses it, not
    # --platform, and its numbers must never resume into a chip run).
    platform_key = {
        "mode": mode,
        "platform_forced": os.environ.get("GRAPHDYN_FORCE_PLATFORM", ""),
        "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
        "xla_flags": os.environ.get("XLA_FLAGS", ""),
    }

    # Resume: a previous (wedge-killed) run's completed configs are kept,
    # not re-measured and never clobbered by the startup flush. A prior
    # file whose platform key mismatches (or that doesn't parse) is moved
    # aside, never silently overwritten — it may hold scarce chip results.
    cached: dict[str, dict] = {}
    prior_backend, prior_devices = "unknown", []
    if os.path.exists(args.out):
        resumable = False
        try:
            with open(args.out) as f:
                prior = json.load(f)
            # Every key field must be PRESENT and equal: a legacy-format doc
            # (no platform fields) records nothing about the env that made
            # it, so it must never resume into any run.
            resumable = (not args.fresh) and isinstance(prior, dict) and all(
                k in prior and prior[k] == v for k, v in platform_key.items())
        except (json.JSONDecodeError, OSError):
            prior = None
        if resumable:
            for entry in prior.get("configs", []):
                if entry.get("rc") == 0 and entry.get("metrics"):
                    cached[entry["config"]] = entry
            prior_backend = prior.get("backend", "unknown")
            prior_devices = prior.get("devices", [])
        else:
            # pid suffix: two same-second move-asides must not clobber
            # each other's backup
            backup = (f"{args.out}.prior-{time.strftime('%Y%m%dT%H%M%S')}"
                      f"-{os.getpid()}")
            os.replace(args.out, backup)
            print(f"prior {args.out} not resumable (platform/mode mismatch, "
                  f"--fresh, or unparseable); moved to {backup}", flush=True)

    doc = {
        "backend": prior_backend,
        "devices": prior_devices,
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "configs": [],
        "ok": False,
        **platform_key,
    }
    names = args.only or CONFIGS
    # The doc always carries every known result — the requested names plus
    # any cached configs outside --only — so a partial re-run can never
    # drop a completed entry from the file.
    all_names = CONFIGS + [n for n in names if n not in CONFIGS]
    all_names += [n for n in cached if n not in all_names]
    # Cached (resumed) entries are part of the doc from the very first
    # flush — a kill at ANY point of this run must not lose them.
    results: dict[str, dict] = dict(cached)

    def flush_doc():
        # Rewrite after every config: an outer timeout killing the
        # aggregator must not discard the configs that already finished.
        doc["configs"] = [results[n] for n in all_names if n in results]
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, args.out)

    flush_doc()
    for name in names:
        if name in cached:
            print(f"=== {name} ({mode}) === cached from previous run", flush=True)
            continue
        print(f"=== {name} ({mode}) ===", flush=True)
        entry = run_config(name, args.full, args.timeout)
        results[name] = entry
        flush_doc()
        for m in entry["metrics"]:
            print("  ", json.dumps(m), flush=True)
        if entry["rc"] != 0:
            print("  rc=%s\n%s" % (entry["rc"], entry.get("stderr_tail", "")), flush=True)

    if doc["backend"] == "unknown":
        # Metadata probe runs last (never spends chip-window time before
        # config 1) and only when the resumed doc didn't already have it.
        doc["backend"], doc["devices"] = probe_device_info()
    ok = all(results.get(n, {}).get("rc") == 0 and results.get(n, {}).get("metrics")
             for n in names)
    doc["ok"] = ok
    flush_doc()
    print(f"WROTE {args.out} ok={ok}")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
