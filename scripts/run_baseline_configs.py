"""Run all five BASELINE benchmark configs and aggregate into one JSON doc.

Each config runs as its own subprocess (fresh JAX runtime — no HBM carryover
between configs; one config crashing cannot take down the rest). The JSON
lines every config prints via ``benchmarks.common.report`` are collected
into a single artifact.

Usage:
  python scripts/run_baseline_configs.py --out BENCH_CONFIGS_r03.json [--full]
  # CPU smoke:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python scripts/run_baseline_configs.py --out smoke.json
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

CONFIGS = [
    "config1_sa_rrg",
    "config2_hpr",
    "config3_er_majority",
    "config4_bdcm_entropy",
    "config5_multichip_sa",
]

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_config(name: str, full: bool, timeout_s: float, platform: str | None) -> dict:
    cmd = [sys.executable, os.path.join(ROOT, "benchmarks", f"{name}.py")]
    if full:
        cmd.append("--full")
    env = dict(os.environ)
    if platform:
        # benchmarks.common applies this before first jax use — survives
        # environment plugins that pin jax_platforms at interpreter startup
        env["GRAPHDYN_FORCE_PLATFORM"] = platform
    t0 = time.time()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s, cwd=ROOT,
            env=env,
        )
        rc, out, err = proc.returncode, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired as e:
        rc, out, err = -1, (e.stdout or ""), f"TIMEOUT after {timeout_s}s"
    metrics = []
    for line in out.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                metrics.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    entry = {
        "config": name,
        "rc": rc,
        "elapsed_s": round(time.time() - t0, 1),
        "metrics": metrics,
    }
    if rc != 0 or not metrics:
        entry["stderr_tail"] = "\n".join(err.splitlines()[-15:])
    return entry


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="BENCH_CONFIGS.json")
    ap.add_argument("--full", action="store_true")
    ap.add_argument("--timeout", type=float, default=3600.0, help="per-config seconds")
    ap.add_argument("--only", nargs="*", help="subset of config names")
    ap.add_argument(
        "--platform", choices=["cpu", "tpu"], default=None,
        help="force the JAX platform in each config subprocess",
    )
    args = ap.parse_args()

    sys.path.insert(0, ROOT)
    if args.platform:
        os.environ["GRAPHDYN_FORCE_PLATFORM"] = args.platform
    import benchmarks.common  # noqa: F401 — applies the platform force
    import jax

    doc = {
        "backend": jax.default_backend(),
        "devices": [str(d) for d in jax.devices()],
        "mode": "full" if args.full else "smoke",
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "configs": [],
    }
    names = args.only or CONFIGS
    for name in names:
        print(f"=== {name} ({doc['mode']}) ===", flush=True)
        entry = run_config(name, args.full, args.timeout, args.platform)
        doc["configs"].append(entry)
        for m in entry["metrics"]:
            print("  ", json.dumps(m), flush=True)
        if entry["rc"] != 0:
            print("  rc=%s\n%s" % (entry["rc"], entry.get("stderr_tail", "")), flush=True)
    ok = all(c["rc"] == 0 and c["metrics"] for c in doc["configs"])
    doc["ok"] = ok
    with open(args.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"WROTE {args.out} ok={ok}")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
