#!/usr/bin/env python3
"""Random-initialization consensus threshold on the SA search's own
ensemble (random d-regular graphs, majority/stay — `SA_RRG.py:45-46`).

Closes the loop on the thesis narrative: the SA solver CONSTRUCTS
initializations at m(0) ≈ 3.7–4.6% that reach all-+1 consensus within the
(p, c) = (3, 1) transient — three synchronous steps (RESULTS_r04.md). This
script measures what a RANDOM biased initialization needs on the same
graphs under the same dynamics, with a generous 2000-step budget (free
dynamics, not the 3-step funnel): the eventual-consensus threshold
m_c^rand. The gap between m_c^rand and SA's 4% — and the fact that SA's
configurations consense in 3 steps rather than hundreds — is the measured
form of "optimized initializations are atypical".

Usage:
  python scripts/physics_consensus_rrg.py OUT_JSON [OUT_PNG] [--full]

Same wedge protection as the other capture scripts.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import benchmarks.common  # noqa: F401 — repo root + platform forcing
from graphdyn.utils.io import write_json_atomic

# bracketing grid: smoke showed the random-init transition sits at
# m(0) ≈ 0.4–0.6 on RRG (vs 0.01 on ER c=6 — degree homogeneity freezes
# domains), so sample densely there while keeping low-m0 anchors
M0_GRID = (0.0, 0.05, 0.1, 0.2, 0.3, 0.4, 0.45, 0.5, 0.55, 0.6, 0.7)
D_GRID = (3, 4)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("out_json")
    ap.add_argument("out_png", nargs="?", default=None)
    ap.add_argument("--full", action="store_true")
    a = ap.parse_args()

    from benchmarks.common import guarded_capture_init

    relay_note = guarded_capture_init()

    import jax  # noqa: F401 — backend recorded via the shared doc writer

    from graphdyn.models.consensus import (
        consensus_curve_ensemble,
        consensus_ensemble_doc,
    )

    n, R, max_steps, seeds = ((10_000, 256, 2000, (0, 1, 2)) if a.full
                              else (3000, 128, 500, (0,)))
    t0 = time.time()
    curves = []
    for d in D_GRID:
        per_seed, agg = consensus_curve_ensemble(
            n, R, M0_GRID, max_steps, graph="rrg", d=d,
            graph_seeds=seeds,
        )
        # each d-curve is one shared-schema ensemble doc (same writer as
        # the CLI and physics_consensus.py — no third schema to drift)
        curves.append({
            "d": d,
            **consensus_ensemble_doc(n, per_seed, agg,
                                     kind="random_regular", d=d),
        })
        print(f"d={d}: " + " ".join(
            f"m0={r['m0']:g}:{r['consensus_fraction_mean']:.2f}"
            for r in agg), flush=True)

    doc = {
        "what": ("random-initialization consensus threshold on RRG "
                 "(the SA ensemble, `SA_RRG.py:45-46`): consensus "
                 "fraction vs m(0) under free majority dynamics"),
        "d_grid": list(D_GRID),
        "replicas": R,
        "max_steps": max_steps,
        "backend": curves[0]["backend"],
        "elapsed_s": round(time.time() - t0, 1),
        "curves": curves,
        **({"relay": relay_note} if relay_note else {}),
    }
    write_json_atomic(a.out_json, doc, indent=1)
    print(f"wrote {a.out_json} (backend={doc['backend']})")

    if a.out_png:
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(9.6, 3.8), dpi=120)
        for cv in curves:
            agg = cv["rows"]
            fr = [r["consensus_fraction_mean"] for r in agg]
            err = [r["consensus_fraction_std"] or 0.0 for r in agg]
            ax1.errorbar([r["m0"] for r in agg], fr, yerr=err, fmt="o-",
                         ms=3.5, lw=1.1, capsize=2, label=f"RRG d={cv['d']}")
            steps = [(r["m0"], r["mean_steps_to_consensus"]) for r in agg
                     if r["mean_steps_to_consensus"] is not None]
            if steps:
                ax2.plot(*zip(*steps), "o-", ms=3.5, lw=1.1,
                         label=f"RRG d={cv['d']}")
        ax1.axvspan(0.037, 0.046, color="tab:red", alpha=0.18,
                    label="SA-constructed m(0) (3-step consensus)")
        ax1.set_xlabel("initial magnetization m(0)")
        ax1.set_ylabel("consensus fraction")
        ax1.set_title(f"random inits, N={n:,}, budget {max_steps} steps",
                      fontsize=9)
        ax1.legend(frameon=False, fontsize=7)
        ax2.set_xlabel("initial magnetization m(0)")
        ax2.set_ylabel("mean steps to consensus")
        ax2.set_title("first-passage (where consensus occurs)", fontsize=9)
        ax2.legend(frameon=False, fontsize=7)
        fig.tight_layout()
        fig.savefig(a.out_png)
        print(f"wrote {a.out_png}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
