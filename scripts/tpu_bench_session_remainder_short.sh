#!/bin/bash
# Late-recovery wrapper: the remainder session with trimmed budgets
# (~1.5h worst case) so it cannot still be holding the chip when the
# driver's round-end bench fires.
SHORT=1 exec bash "$(dirname "$0")/tpu_bench_session_remainder.sh" "$@"
