#!/bin/bash
# Remainder chip session: the stages the first round-4 window did NOT get
# before the relay wedged at 10:19 UTC (headline bench + gather A/B + DMA
# probe are already captured in tpu_session_r04/). Ordered by evidence
# value so a second wedge mid-session still leaves the most important
# artifact behind:
#   1. five BASELINE configs at full scale (the VERDICT item-1 "done" bar)
#   2. on-chip HPr physics at reference constants
#   3. Pallas on-chip validation refresh (round-3 chip data already exists)
# Idempotent per stage (see _session_lib.sh): refires skip captured
# artifacts and re-run only what is missing.
# SHORT=1 trims per-stage budgets for a late recovery (cannot collide with
# the driver's own round-end bench).  Usage:
#   bash scripts/tpu_bench_session_remainder.sh [outdir]
set -u
cd "$(dirname "$0")/.."
. scripts/_session_lib.sh
OUT="${1:-tpu_session_r05}"
mkdir -p "$OUT"

if [ "${SHORT:-0}" = "1" ]; then
    CFG_OUTER=3600; CFG_PER=650; PHYS=600; VALIDATE=0
else
    CFG_OUTER=9000; CFG_PER=1500; PHYS=1200; VALIDATE=1500
fi

# 'axon' = the tunneled-TPU plugin: chip-or-hang in every stage, so a
# relay that half-recovers can never let JAX fall back to CPU and write
# CPU rates into the chip artifacts (per-config/outer timeouts bound the
# hang; the aggregator resumes whatever completed on the next firing).
echo "[tpu-remainder] five BASELINE configs (full, per-config ${CFG_PER}s) ..." >&2
timeout "$CFG_OUTER" python scripts/run_baseline_configs.py \
    --out "$OUT/configs_tpu.json" --full --timeout "$CFG_PER" --platform axon >&2
echo "[tpu-remainder] configs rc=$?" >&2

if json_ok "$OUT/physics_tpu.json"; then
    echo "[tpu-remainder] physics already captured; skipping" >&2
else
    echo "[tpu-remainder] physics on chip (HPr at reference constants) ..." >&2
    GRAPHDYN_FORCE_PLATFORM=axon timeout "$PHYS" \
        python scripts/physics_r04.py hpr "$OUT/physics_tpu.json" \
        > "$OUT/physics_tpu.log" 2>&1
    echo "[tpu-remainder] physics rc=$?" >&2
fi

if chip_doc_ok "$OUT/consensus_tpu.json"; then
    echo "[tpu-remainder] consensus physics already captured; skipping" >&2
else
    echo "[tpu-remainder] ER-majority consensus physics (m0 sweep) ..." >&2
    # instances scale with the budget; no per-instance resume, so a
    # timeout loses the whole sweep — size it to fit
    if [ "${SHORT:-0}" = "1" ]; then CONS_T=900; CONS_I=1; else CONS_T=2700; CONS_I=3; fi
    GRAPHDYN_FORCE_PLATFORM=axon timeout "$CONS_T" \
        python scripts/physics_consensus.py \
        "$OUT/consensus_tpu.json" "$OUT/consensus_tpu.png" --full \
        --instances "$CONS_I" \
        > "$OUT/consensus_tpu.log" 2>&1
    echo "[tpu-remainder] consensus rc=$?" >&2
fi

if [ "$VALIDATE" -gt 0 ]; then
    if json_ok "$OUT/PALLAS_TPU.json"; then
        echo "[tpu-remainder] pallas validation already captured; skipping" >&2
    else
        echo "[tpu-remainder] pallas on-chip validation ..." >&2
        GRAPHDYN_FORCE_PLATFORM=axon timeout "$VALIDATE" \
            python scripts/pallas_tpu_validate.py \
            > "$OUT/pallas_validate.log" 2>&1
        rc=$?
        echo "[tpu-remainder] pallas validate rc=$rc" >&2
        [ $rc -eq 0 ] && cp -f PALLAS_TPU.json "$OUT/PALLAS_TPU.json"
    fi
fi

collect_round "$OUT" tpu-remainder
echo "[tpu-remainder] done; artifacts in $OUT" >&2
