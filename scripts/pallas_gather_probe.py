#!/usr/bin/env python3
"""Random-row gather probe: XLA take vs Pallas explicit row-DMA.

The packed dynamics kernel's gap to its HBM-streaming roofline is gather
*randomness* (ARCHITECTURE.md): per step it reads ``n·d`` randomly-indexed
``[1, W]`` uint32 rows. This probe grounds the roofline refinement in
measurements, answering two questions on the real chip:

1. What random-row rate (rows/s and effective GB/s) does XLA's native
   gather achieve as a function of row width W? If rows/s is ~constant in
   W, the kernel is ACCESS-RATE-bound, not bandwidth-bound — and the
   wide-replica lever in bench.py (4× W ⇒ ~4× headline) is the fix, no
   custom kernel needed.
2. Can a Pallas kernel with explicitly pipelined per-row HBM→VMEM DMAs
   (depth-S double buffering, the guide's sparse-gather pattern) beat the
   XLA gather at the same shape? If the two land close, XLA is already at
   the hardware's random-access limit and the written analysis closes
   VERDICT r3 task 8; if Pallas wins big, it graduates into the dynamics
   kernel.

Runs in interpret mode off-TPU (correctness only); rates are meaningful on
chip. Emits one JSON line per (impl, W) combo.
"""

import argparse
import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import timed   # applies GRAPHDYN_FORCE_PLATFORM

import numpy as np

import jax
import jax.numpy as jnp


def _draw(n_src, n_idx, W, seed):
    """Source rows + indices drawn ON DEVICE — a host upload of the source
    (4 GB at W=1024) over the tunneled link wedges the relay (r04 session)."""
    from benchmarks.common import draw_u32

    src = draw_u32(seed, (n_src, W))
    idx = jax.jit(
        lambda: jax.random.randint(
            jax.random.key(seed + 1), (n_idx,), 0, n_src, jnp.int32
        )
    )()
    jax.block_until_ready(idx)
    return src, idx


def xla_gather_rate(n_src, n_idx, W, iters=3, seed=0):
    src, idx = _draw(n_src, n_idx, W, seed)
    f = jax.jit(lambda s, i: jnp.take(s, i, axis=0))
    out, dt = timed(f, src, idx, iters=iters)
    return n_idx / dt, n_idx * W * 4 / dt, out


def pallas_gather(src, idx, *, block=256, depth=8, interpret=False):
    """out[i] = src[idx[i]] via per-row async copies, depth-``depth``
    pipelined. Grid tiles the index vector; indices ride in SMEM; the source
    stays in HBM (memory_space=ANY) and rows land in a VMEM ring buffer."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_idx = idx.shape[0]
    W = src.shape[1]
    assert n_idx % block == 0

    def kernel(idx_ref, src_ref, out_ref, scratch, sems):
        def dma(k):
            slot = jax.lax.rem(k, depth)
            return pltpu.make_async_copy(
                src_ref.at[pl.ds(idx_ref[k], 1), :],
                scratch.at[pl.ds(slot, 1), :],
                sems.at[slot],
            )

        def warm(k, _):
            dma(k).start()
            return 0

        jax.lax.fori_loop(0, min(depth, block), warm, 0)

        def body(k, _):
            dma(k).wait()
            slot = jax.lax.rem(k, depth)
            out_ref[pl.ds(k, 1), :] = scratch[pl.ds(slot, 1), :]

            # refill the slot only AFTER its row is consumed (k+depth shares
            # slot(k)); lookahead depth-1 DMAs stay in flight
            @pl.when(k + depth < block)
            def _():
                dma(k + depth).start()

            return 0

        jax.lax.fori_loop(0, block, body, 0)

    return pl.pallas_call(
        kernel,
        grid=(n_idx // block,),
        in_specs=[
            pl.BlockSpec((block,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((block, W), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_idx, W), src.dtype),
        scratch_shapes=[
            pltpu.VMEM((depth, W), src.dtype),
            pltpu.SemaphoreType.DMA((depth,)),
        ],
        interpret=interpret,
    )(idx, src)


def pallas_gather_rate(n_src, n_idx, W, iters=3, seed=0, depth=8, interpret=False):
    src, idx = _draw(n_src, n_idx, W, seed)
    f = jax.jit(functools.partial(pallas_gather, depth=depth, interpret=interpret))
    out, dt = timed(f, src, idx, iters=iters)
    return n_idx / dt, n_idx * W * 4 / dt, out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n-src", type=int, default=1_000_000)
    ap.add_argument("--n-idx", type=int, default=3 * 1_000_000)
    ap.add_argument("--widths", type=int, nargs="+", default=[128, 512, 1024])
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--check", action="store_true",
                    help="small-shape correctness check (interpret off-TPU)")
    args = ap.parse_args()

    on_tpu = jax.default_backend() == "tpu"
    if args.check or not on_tpu:
        rng = np.random.default_rng(1)
        src = jnp.asarray(rng.integers(0, 2**32, size=(512, 128), dtype=np.uint32))
        idx = jnp.asarray(rng.integers(0, 512, size=1024).astype(np.int32))
        out = pallas_gather(src, idx, block=256, depth=4, interpret=not on_tpu)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(src)[np.asarray(idx)])
        print(json.dumps({"check": "ok", "backend": jax.default_backend()}))
        if not on_tpu:
            return 0

    for W in args.widths:
        # constant gathered BYTES across widths (and block-aligned), so the
        # rows/s-vs-W trend isolates the access-rate question; also keeps
        # the W=1024 output buffer inside a v5e's 16 GB HBM
        n_idx = max(256, (args.n_idx * 128 // W) // 256 * 256)
        try:
            rows, bw, out_x = xla_gather_rate(args.n_src, n_idx, W)
            print(json.dumps({
                "impl": "xla_take", "W": W, "n_idx": n_idx,
                "rows_per_s": rows, "GBps": bw / 1e9,
            }), flush=True)
        except Exception as e:  # noqa: BLE001 — record (e.g. OOM), keep probing
            print(json.dumps({
                "impl": "xla_take", "W": W, "error": str(e)[:300],
            }), flush=True)
            continue
        try:
            prows, pbw, out_p = pallas_gather_rate(
                args.n_src, n_idx, W, depth=args.depth
            )
            match = bool(jnp.array_equal(out_x, out_p))   # device-side; one
            print(json.dumps({                            # scalar to host
                "impl": "pallas_row_dma", "W": W, "depth": args.depth,
                "n_idx": n_idx,
                "rows_per_s": prows, "GBps": pbw / 1e9, "matches_xla": match,
            }), flush=True)
        except Exception as e:  # noqa: BLE001 — record, keep probing
            print(json.dumps({
                "impl": "pallas_row_dma", "W": W, "error": str(e)[:300],
            }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
