#!/usr/bin/env python3
"""Golden-curve artifact at reference precision (round-4 deliverable).

Runs the notebook's exact configuration — n=1000, mean degree 1.0 (the
networkx `fast_gnp_random_graph` sampler for distribution parity,
`ER_BDCM_entropy.ipynb:280`), λ ladder 0..12 step 0.1, damp 0.1, eps 1e-6 —
in float64 (the reference's numpy precision) over several seeds, and writes
``GOLDEN_r04.json``: the per-seed (λ, m_init, ent1) tables plus
instance-to-instance spread statistics at the ten stored golden triples
(`ipynb:18-46`, BASELINE.md). The stored reference run is a single unseeded
instance, so the right acceptance bar is "the golden values sit inside the
measured instance spread" — asserted by the slow test this file feeds
(tests/test_entropy.py).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from graphdyn.utils.platform import apply_force_platform

apply_force_platform()

import jax

jax.config.update("jax_enable_x64", True)

import numpy as np

from graphdyn.config import EntropyConfig
from graphdyn.graphs import erdos_renyi_graph
from graphdyn.models.entropy import entropy_sweep
from graphdyn.utils.io import write_json_atomic

# `ER_BDCM_entropy.ipynb:18-46` stored stream output (full precision,
# BASELINE.md) — the only numeric ground truth in the reference repo.
GOLDEN = {
    0.0: (0.7859766580538275, 0.1720699495590459),
    0.1: (0.7699358367558866, 0.17127259171924963),
    0.2: (0.7545492129205356, 0.16897079877838897),
    0.3: (0.7399806499309954, 0.16533606458353123),
    0.4: (0.7263552613663471, 0.1605754636000715),
    0.5: (0.7137593656167142, 0.15491615729839237),
    0.6: (0.7022428278329915, 0.14859118078564132),
    0.7: (0.6918229572378949, 0.14182740343380668),
    0.8: (0.6824890587925729, 0.1348359237835574),
    0.9: (0.6742072244439773, 0.12780494062947345),
}


def main(n_seeds: int = 8, out_path: str = "GOLDEN_r04.json") -> None:
    cfg = EntropyConfig(dtype="float64")   # λ 0..12 step .1, damp .1, eps 1e-6
    rows = []
    for seed in range(n_seeds):
        g = erdos_renyi_graph(1000, 1.0 / 999, seed=seed, method="networkx")
        n_iso = int((g.deg == 0).sum())
        t0 = time.time()
        res = entropy_sweep(g, cfg, seed=seed)
        elapsed = time.time() - t0
        rows.append({
            "seed": seed,
            "n_isolated": n_iso,
            "mean_degree": float(g.deg.mean()),
            "lambdas": np.round(res.lambdas, 10).tolist(),
            "m_init": res.m_init.tolist(),
            "ent1": res.ent1.tolist(),
            "sweeps": res.sweeps.tolist(),
            "nonconverged": float(res.nonconverged),
            "elapsed_s": round(elapsed, 1),
        })
        print(
            f"seed {seed}: {res.lambdas.size} lambda-points, "
            f"{n_iso} isolates, {elapsed:.1f}s",
            flush=True,
        )

    spread = {}
    for lam, (mg, eg) in GOLDEN.items():
        ms, es = [], []
        for r in rows:
            lam_arr = np.round(np.asarray(r["lambdas"]), 2)
            idx = np.where(lam_arr == round(lam, 2))[0]
            if idx.size:
                ms.append(r["m_init"][int(idx[0])])
                es.append(r["ent1"][int(idx[0])])
        ms, es = np.asarray(ms), np.asarray(es)
        spread[f"{lam:.1f}"] = {
            "golden_m_init": mg,
            "golden_ent1": eg,
            "m_init": {"mean": ms.mean(), "std": ms.std(), "min": ms.min(), "max": ms.max()},
            "ent1": {"mean": es.mean(), "std": es.std(), "min": es.min(), "max": es.max()},
            "golden_m_init_inside_spread": bool(ms.min() <= mg <= ms.max()),
            "golden_ent1_inside_spread": bool(es.min() <= eg <= es.max()),
            "golden_m_init_z": float((mg - ms.mean()) / max(ms.std(), 1e-12)),
            "golden_ent1_z": float((eg - es.mean()) / max(es.std(), 1e-12)),
        }

    out = {
        "config": {
            "n": 1000, "mean_degree": 1.0, "sampler": "networkx",
            "p": 1, "c": 1, "damp": 0.1, "eps": 1e-6, "dtype": "float64",
            "lambda_ladder": "0..12 step 0.1", "n_seeds": n_seeds,
            "reference": "ER_BDCM_entropy.ipynb:18-46 stored stream output",
        },
        "spread_at_golden_lambdas": spread,
        "per_seed": rows,
    }
    write_json_atomic(out_path, out, indent=1, default=float)
    print(f"wrote {out_path}", flush=True)


if __name__ == "__main__":
    main(
        n_seeds=int(sys.argv[1]) if len(sys.argv) > 1 else 8,
        out_path=sys.argv[2] if len(sys.argv) > 2 else "GOLDEN_r04.json",
    )
