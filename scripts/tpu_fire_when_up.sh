#!/bin/bash
# Watch the TPU canary log; whenever an UP line appears, fire the chip
# session into the given outdir. The session may be cut short by a relay
# re-wedge, so the watcher re-arms and fires again on the next recovery —
# the config aggregator resumes completed configs, making refires cheap —
# until the session reports done or MAX_FIRES firings are spent (a
# flapping relay must not burn chip time in a loop forever).
# After FULL_UNTIL (epoch seconds; 0 = always full) the abbreviated
# session runs instead — a multi-hour full session fired late would
# still be holding the chip when the driver's own round-end bench runs.
#   nohup bash scripts/tpu_fire_when_up.sh tpu_session_r04 [log] [full_until] &
# Env: SESSION_SCRIPT / SESSION_SCRIPT_LATE override the session scripts;
#      MAX_FIRES caps firings (default 3);
#      DONE_CHECK is a shell command returning 0 when no refire is needed
#      (default: configs_tpu.json in the outdir reports ok=true).
cd "$(dirname "$0")/.."
OUT="${1:-tpu_session_r04}"
LOG="${2:-/tmp/tpu_canary.log}"
FULL_UNTIL="${3:-0}"
FLAG="$OUT/.fired"
MAX_FIRES="${MAX_FIRES:-3}"
# Done = configs suite ok AND physics artifact parses (a timeout-truncated
# physics file must keep a refire available) AND the consensus artifact is
# chip-valid (backend tpu/axon, no fallback label), OR a session that
# produces none of those (the abbreviated bench-only one) self-reported
# completion.
DONE_CHECK="${DONE_CHECK:-[ -f '$OUT/.short_session_done' ] || python -c \"import json; d=json.load(open('$OUT/configs_tpu.json')); json.load(open('$OUT/physics_tpu.json')); c=json.load(open('$OUT/consensus_tpu.json')); exit(0 if d.get('ok') and c.get('backend') in ('tpu','axon') and 'relay' not in c else 1)\" 2>/dev/null}"
mkdir -p "$OUT"
while true; do
    FIRES=$( [ -f "$FLAG" ] && wc -l < "$FLAG" || echo 0 )
    if [ "$FIRES" -ge "$MAX_FIRES" ]; then
        echo "[fire-when-up] $FIRES firings spent; exiting" >> "$OUT/session.log"
        exit 0
    fi
    if eval "$DONE_CHECK"; then
        echo "[fire-when-up] done-check passed; exiting" >> "$OUT/session.log"
        exit 0
    fi
    if tail -n 1 "$LOG" 2>/dev/null | grep -q "EXPIRED"; then
        # the canary stopped probing — nothing will ever flip the log to UP,
        # so waiting on it is pointless; exit rather than poll a dead file
        echo "[fire-when-up] canary expired; exiting unfired" >> "$OUT/session.log"
        exit 0
    fi
    if tail -n 1 "$LOG" 2>/dev/null | grep -q " UP "; then
        SESSION="${SESSION_SCRIPT:-scripts/tpu_bench_session.sh}"
        if [ "$FULL_UNTIL" -gt 0 ] && [ "$(date +%s)" -gt "$FULL_UNTIL" ]; then
            # default the late session to the short variant of the MAIN
            # session (<name>_short.sh); if none exists, keep the main
            # session rather than fall back to an unrelated script
            DERIVED="${SESSION%.sh}_short.sh"
            [ -f "$DERIVED" ] || DERIVED="$SESSION"
            SESSION="${SESSION_SCRIPT_LATE:-$DERIVED}"
        fi
        if [ ! -f "$SESSION" ]; then
            # validate BEFORE burning a firing: a mistyped SESSION_SCRIPT
            # must not consume the recovery window
            echo "[fire-when-up] session script $SESSION missing; NOT firing" \
                >> "$OUT/session.log"
            exit 1
        fi
        date -u >> "$FLAG"
        trap 'rm -f /tmp/tpu_canary.pause' EXIT   # unpause even if killed
        touch /tmp/tpu_canary.pause      # the session owns the chip now
        echo "[fire-when-up] canary UP at $(date -u +%H:%M:%S); launching $SESSION" \
            "(firing $((FIRES + 1))/$MAX_FIRES)" >> "$OUT/session.log"
        bash "$SESSION" "$OUT" >> "$OUT/session.log" 2>&1
        rm -f /tmp/tpu_canary.pause
        # loop (don't exit): the done/max-fires checks at the top decide
        # whether another recovery window should refire. Wait out a FULL
        # canary cycle (120s interval + 90s probe timeout) so a stale UP
        # line from before a fast-failing session can't refire into a
        # relay that wedged during it.
        sleep 240
    fi
    sleep 30
done
