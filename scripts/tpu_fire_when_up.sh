#!/bin/bash
# Watch the TPU canary log; the first time an UP line appears, fire the
# one-shot chip session into the given outdir (exactly once) and exit.
# After FULL_UNTIL (epoch seconds; 0 = always full) the abbreviated
# session runs instead — a multi-hour full session fired late would
# still be holding the chip when the driver's own round-end bench runs.
#   nohup bash scripts/tpu_fire_when_up.sh tpu_session_r04 [log] [full_until] &
cd "$(dirname "$0")/.."
OUT="${1:-tpu_session_r04}"
LOG="${2:-/tmp/tpu_canary.log}"
FULL_UNTIL="${3:-0}"
FLAG="$OUT/.fired"
mkdir -p "$OUT"
while true; do
    if [ -f "$FLAG" ]; then exit 0; fi
    if tail -n 1 "$LOG" 2>/dev/null | grep -q "EXPIRED"; then
        # the canary stopped probing — nothing will ever flip the log to UP,
        # so waiting on it is pointless; exit rather than poll a dead file
        echo "[fire-when-up] canary expired; exiting unfired" >> "$OUT/session.log"
        exit 0
    fi
    if tail -n 1 "$LOG" 2>/dev/null | grep -q " UP "; then
        SESSION="${SESSION_SCRIPT:-scripts/tpu_bench_session.sh}"
        if [ "$FULL_UNTIL" -gt 0 ] && [ "$(date +%s)" -gt "$FULL_UNTIL" ]; then
            # default the late session to the short variant of the MAIN
            # session (<name>_short.sh); if none exists, keep the main
            # session rather than fall back to an unrelated script
            DERIVED="${SESSION%.sh}_short.sh"
            [ -f "$DERIVED" ] || DERIVED="$SESSION"
            SESSION="${SESSION_SCRIPT_LATE:-$DERIVED}"
        fi
        if [ ! -f "$SESSION" ]; then
            # validate BEFORE burning the one-shot flag: a mistyped
            # SESSION_SCRIPT must not consume the recovery window
            echo "[fire-when-up] session script $SESSION missing; NOT firing" \
                >> "$OUT/session.log"
            exit 1
        fi
        date -u > "$FLAG"
        trap 'rm -f /tmp/tpu_canary.pause' EXIT   # unpause even if killed
        touch /tmp/tpu_canary.pause      # the session owns the chip now
        echo "[fire-when-up] canary UP at $(date -u +%H:%M:%S); launching $SESSION" \
            >> "$OUT/session.log"
        bash "$SESSION" "$OUT" >> "$OUT/session.log" 2>&1
        rm -f /tmp/tpu_canary.pause
        exit 0
    fi
    sleep 30
done
