#!/usr/bin/env python3
"""Physics end-to-end at reference scale (round-4 deliverable).

Two committed demonstrations that the solvers actually solve the thesis
problem at the reference's own constants, not just at test scale:

(a) SA at `SA_RRG.py:44-56`: n=10⁴, d=4, p=3, c=1, a₀=0.015n, b₀=0.01n,
    anneal ×1.0005 capped at 4.5n/5n — chains run until
    m(s_endstate) = 1 and report the achieved initial magnetization
    ``mag_reached`` and step count (`SA_RRG.py:86-88`).
(b) HPr at `HPR_pytorch_RRG.py:222-237`: n=10⁴, d=4, p=c=1, λ_eff=25,
    π=0.3, γ=0.1 — run to consensus, report sweep count and wall-clock
    (the reference's persisted `time`, `HPR:364`).

Writes ``physics_r04.json``; RESULTS_r04.md summarizes it.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from graphdyn.utils.platform import apply_force_platform

apply_force_platform()

import numpy as np

from graphdyn.config import DynamicsConfig, HPRConfig, SAConfig
from graphdyn.graphs import random_regular_graph
from graphdyn.models.hpr import hpr_solve
from graphdyn.models.sa import simulated_annealing
from graphdyn.ops.dynamics import end_state
from graphdyn.utils.io import write_json_atomic


def run_sa(n=10_000, d=4, replicas=4, max_steps=100_000_000, out=None):
    import jax

    g = random_regular_graph(n, d, seed=0)
    cfg = SAConfig(dynamics=DynamicsConfig(p=3, c=1), max_steps=max_steps)
    t0 = time.time()
    res = simulated_annealing(
        g, cfg, n_replicas=replicas, seed=0, rollout_mode="lightcone"
    )
    elapsed = time.time() - t0
    rows = []
    for r in range(replicas):
        verified = bool(
            np.all(np.asarray(end_state(g, res.s[r], 3, 1, backend="cpu")) == 1)
        ) if res.m_final[r] == 1.0 else False
        rows.append({
            "replica": r,
            "m_final": float(res.m_final[r]),
            "mag_reached": float(res.mag_reached[r]),
            "num_steps": int(res.num_steps[r]),
            "endstate_all_plus1_verified": verified,
        })
        print(f"SA replica {r}: m_final={res.m_final[r]} "
              f"mag_reached={res.mag_reached[r]:.4f} steps={res.num_steps[r]} "
              f"verified={verified}", flush=True)
    result = {
        "task": "SA at reference constants (SA_RRG.py:44-56)",
        "n": n, "d": d, "p": 3, "c": 1, "replicas": replicas,
        "max_steps": max_steps, "platform": jax.default_backend(),
        "elapsed_s": round(elapsed, 1),
        "chains": rows,
        "consensus_fraction": float(np.mean([r["m_final"] == 1.0 for r in rows])),
        "median_steps_to_consensus": (
            float(np.median([r["num_steps"] for r in rows if r["m_final"] == 1.0]))
            if any(r["m_final"] == 1.0 for r in rows) else None
        ),
    }
    if out:
        _merge(out, "sa", result)
    return result


def run_hpr(n=10_000, d=4, out=None):
    import jax

    g = random_regular_graph(n, d, seed=0)
    cfg = HPRConfig(dynamics=DynamicsConfig(p=1, c=1))   # TT=10^4, λ_eff=25
    t0 = time.time()
    res = hpr_solve(g, cfg, seed=0)
    elapsed = time.time() - t0
    verified = bool(
        np.all(np.asarray(end_state(g, res.s, 1, 1, backend="cpu")) == 1)
    ) if res.m_final == 1.0 else False
    print(f"HPr: m_final={res.m_final} mag_reached={float(res.mag_reached):.4f} "
          f"sweeps={res.num_steps} wall={elapsed:.1f}s verified={verified}",
          flush=True)
    result = {
        "task": "HPr at reference constants (HPR_pytorch_RRG.py:222-237)",
        "n": n, "d": d, "p": 1, "c": 1,
        "platform": jax.default_backend(),
        "m_final": float(res.m_final),
        "mag_reached": float(res.mag_reached),
        "num_sweeps": int(res.num_steps),
        "wall_clock_s": round(elapsed, 1),
        "endstate_all_plus1_verified": verified,
    }
    if out:
        _merge(out, "hpr", result)
    return result


def _merge(path, key, value):
    data = {}
    if os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
    data[key] = value
    write_json_atomic(path, data, indent=1)
    print(f"updated {path} [{key}]", flush=True)


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    out = sys.argv[2] if len(sys.argv) > 2 else "physics_r04.json"
    if which in ("hpr", "both"):
        run_hpr(out=out)
    if which in ("sa", "both"):
        run_sa(out=out)
