#!/bin/bash
# TPU relay canary: append one status line per probe to the log. Each probe
# is a fresh interpreter (the wedge hits at client setup, so a persistent
# process would only measure its own cached connection). Usage:
#   nohup bash scripts/tpu_canary.sh [logfile] [interval_s] [max_age_s] &
# After max_age_s (default 8h) the canary logs EXPIRED and exits, so a stray
# probe cannot collide with a later chip run it knows nothing about.
LOG="${1:-/tmp/tpu_canary.log}"
INT="${2:-120}"
MAX_S="${3:-28800}"     # self-expire (default 8h): a probe colliding with
T0=$(date +%s)          # the driver's own round-end chip run could wedge it
cd "$(dirname "$0")/.."
while true; do
    if [ $(( $(date +%s) - T0 )) -ge "$MAX_S" ]; then
        echo "$(date -u +%H:%M:%S) EXPIRED after ${MAX_S}s" >> "$LOG"
        exit 0
    fi
    # a bench session owns the chip exclusively: probing while it runs both
    # contends for the device and pollutes its timings — pause instead
    if [ -f /tmp/tpu_canary.pause ]; then
        echo "$(date -u +%H:%M:%S) PAUSED" >> "$LOG"
        sleep "$INT"
        continue
    fi
    out=$(timeout 90 python - <<'EOF' 2>/dev/null
import jax, time
t0 = time.time()
d = jax.devices()
x = jax.numpy.ones((128, 128)) @ jax.numpy.ones((128, 128))
x.block_until_ready()
print(f"UP {d[0].platform} {time.time()-t0:.1f}s")
EOF
    )
    rc=$?
    if [ $rc -ne 0 ] || [ -z "$out" ]; then out="DOWN rc=$rc"; fi
    echo "$(date -u +%H:%M:%S) $out" >> "$LOG"
    sleep "$INT"
done
