"""On-TPU validation + timing of the fused Pallas BDCM kernel.

Runs the test_pallas equivalence matrix in compiled (non-interpret) mode on
the real chip, then times XLA class_update vs Pallas dp_contract across a
(d, T, Ed) grid to replace the `pallas_supported` guess with measured
crossovers. Emits one JSON document (stdout + PALLAS_TPU.json) consumed by
PALLAS_TPU.md.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python scripts/pallas_tpu_validate.py

CHIP-ROUND CHECKLIST (run alongside this script the first session a real
TPU answers — no chip round has landed since r05, and several committed
bands are provisional until one does):

1. ``python bench.py`` (full, not --smoke) — persists the round's
   ``BENCH_r*.json`` with the fingerprint summary, ``peak_hbm_bytes``,
   and the halo weak-scaling row (``halo_weak_efficiency`` measures for
   real on >= 2 chips; the CPU container can only null+reason it).
2. ``python -m graphdyn.obs memcheck`` — the FIRST run with usable
   ``memory_stats()``: measured peaks land against the byte models and
   the provisional ``MEM_BANDS`` (packed_state / bdcm_stack /
   entropy_cell_chunk / halo_shard) re-center on data — update the bands
   + the ARCHITECTURE.md table in the same reviewed PR.
3. ``python -m graphdyn.obs check`` on-chip — ``CHIP_BANDS``
   (obs/roofline.py, seeded from the published 819 GB/s v5e anchor)
   re-center the same way; an uncalibrated device kind shows up as the
   explicit ``obs.roofline.uncalibrated`` gauge.
4. Bless deliberate rate shifts: ``python -m graphdyn.obs trend ROW.json
   --bless`` (OBS_TREND.json), so the next round's trend gate diffs
   against measured chip numbers instead of CPU smoke rows.
5. Search-acceleration A/B on chip: the ``tta_tempering`` /
   ``tta_chromatic`` rows of step 1's full bench run measure on real
   lanes (device-step counts are seed-deterministic, so they must MATCH
   the CPU rows bit-for-bit — a mismatch means a backend-dependent
   search-chain divergence, which is a bug, not noise); confirm
   ``swap_acceptance_rate`` lands in the committed 0.2–0.9 healthy band
   at the full shape and record the measured wall-clock per leg from the
   round's obs ledger (``bench.tta`` spans) next to the step counts.
6. One-kernel annealer on chip (first COMPILED run of
   ``ops/pallas_anneal`` — the CPU container can only interpret it):
   (a) ``fused_anneal(kernel='pallas')`` at the graftcheck canonical
   shape (RRG n=48 d=3, R=32, 4 sweeps) must be bit-identical to
   ``kernel='xla'`` on the same seeds — state, ``Σs_end``, first
   passages, accept counts (the tier-1 interpret-parity test, now
   compiled; the counter RNG is integer arithmetic, so any divergence
   is a lowering bug, not float noise); if Mosaic rejects the in-kernel
   gathers, confirm the ``resilient_exec`` fallback rebuilds to the XLA
   twin and record WHICH construct failed — that answer scopes the v2
   kernel. (b) step 1's ``fused_sa_rate`` row measures for real
   (null+reason on CPU): record proposals/s vs the packed-rollout
   headline and vs ``tta_tempering``'s per-leg wall clock, and
   re-center ``FUSED_VMEM_BUDGET`` if the compiler's scoped-vmem charge
   differs from the ``fused_vmem_bytes`` model by more than the
   documented ~33% margin. (c) the ``tta_fused`` device-step counts
   must match the CPU rows bit-for-bit (same contract as item 5).
7. Re-center the cost ledger on chip: run ``python -m
   graphdyn.analysis.graftcost --update-ledger`` on the TPU backend and
   commit the chip-stamped ``COST_LEDGER.json`` (the cpu-backend gate
   keeps its own diff; the chip rows are what ``obs memcheck``'s
   ``derived:*`` cross-check and bench's ``derived_bytes`` /
   ``arithmetic_intensity`` columns evaluate on-chip). Then re-center
   ``graftcost.DERIVED_MEM_BANDS`` (provisional, like ``MEM_BANDS``) on
   the measured ``memory_stats()`` peaks from step 2, and sanity-check
   the blessed ``fused_vmem_bytes`` GB102 ratio against the compiler's
   scoped-vmem charge from item 6(b) — all three updates in the same
   reviewed PR as the band re-centering.
8. Sharded streaming on real chips: step 1's full bench run measures
   ``stream_shard_scaling`` (fixed nodes/shard, P ∈ {1,2,4,8}, fixed
   per-shard budget so every leg actually streams) and
   ``churn_repartition_rate`` for the first time on hardware where the
   P legs do not share two host cores — the CPU smoke efficiency is an
   honesty check only. Compare the per-shard streamed rate against the
   single-chip ``stream_rate`` row: the gap is the exchange tax of the
   composed engine (ppermute slab + hub ring riding the chunk walk),
   and the per-shard ``stream.overlap_util`` gauges say whether the
   prefetch still hides the H2D seam once the ICI exchange shares the
   step. A weak-scaling efficiency well below the resident
   ``halo_weak_efficiency`` at the same P means the chunk-boundary
   exchange is serializing against the prefetch — file it against the
   slab schedule, not the partitioner.
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from graphdyn.utils.platform import apply_force_platform

apply_force_platform()

import numpy as np

import jax
import jax.numpy as jnp

from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.utils.io import write_json_atomic
from graphdyn.ops.bdcm import BDCMData, class_update, make_sweep
from graphdyn.ops.pallas_bdcm import (
    LANE,
    VMEM_BUDGET,
    dp_contract,
    dp_contract_grouped,
    pallas_group_supported,
    pallas_supported,
    vmem_block_edges,
)

EQUIV_MATRIX = [(1, 2), (2, 2), (3, 2), (4, 2), (5, 2), (6, 2), (8, 2), (3, 3), (4, 3), (2, 4)]
TIMING_GRID_DT = [(3, 2), (4, 2), (5, 2), (3, 3), (4, 3), (2, 4)]
TIMING_GRID_ED = [512, 4096, 32768, 131072]
# grouped grid: (d, T, G) — equivalence + VMEM-model check per point; G
# spans the drivers' default group sizes and the model's 0-fallback edge
GROUP_MATRIX = [
    (3, 2, 1), (3, 2, 8), (3, 2, 32), (4, 2, 8), (3, 3, 8), (2, 4, 4),
    (4, 3, 8), (3, 4, 8), (3, 4, 32),   # (3,4,32): group-resident stack
    #                                     crowds out the lane tile -> 0
]


def _inputs(d, T, Ed, seed=7):
    rng = np.random.default_rng(seed)
    K = 2**T
    M = (d + 1) ** T
    chi_in = jnp.asarray(rng.random((Ed, d, K, K)), jnp.float32)
    A = jnp.asarray(rng.random((K, K, M)), jnp.float32)
    chi_old = jnp.asarray(rng.random((Ed, K, K)), jnp.float32)
    return chi_in, A, chi_old


def _xla_ref(chi_in, A, chi_old, d, T, damp, eps):
    K = 2**T
    tilt = jnp.ones((K,), jnp.float32)
    return class_update(
        chi_in, A, tilt, chi_old, d=d, T=T, K=K, damp=damp, eps_clamp=eps
    )


def equivalence():
    out = []
    damp, eps = 0.3, 0.0
    for d, T in EQUIV_MATRIX:
        Ed = 1000
        supported = pallas_supported(d, T, Ed)
        row = {
            "d": d,
            "T": T,
            "Ed": Ed,
            "supported": supported,
            "vmem_block_edges": vmem_block_edges(d, T),
        }
        if supported:
            chi_in, A, chi_old = _inputs(d, T, Ed)
            ref = _xla_ref(chi_in, A, chi_old, d, T, damp, eps)
            got = dp_contract(chi_in, A, chi_old, d=d, T=T, damp=damp, eps_clamp=eps)
            err = float(jnp.max(jnp.abs(got - ref)))
            rel = float(jnp.max(jnp.abs(got - ref) / jnp.maximum(jnp.abs(ref), 1e-30)))
            row.update(max_abs_err=err, max_rel_err=rel, ok=bool(err < 1e-3))
        out.append(row)
        print("equiv", row, flush=True)
    return out


def grouped_equivalence():
    """Compiled-mode checks of the GROUPED kernel (group axis as grid dim)
    per (d, T, G): grouped-vs-XLA max rel err for the shared and the
    group-resident A variants, grouped-G>1-vs-G=1 bit-equality, and the
    VMEM model's verdict (a point the model rejects records the honest
    0-fallback instead of launching)."""
    out = []
    damp = 0.3
    for d, T, G in GROUP_MATRIX:
        Ed = 1000
        K, M = 2**T, (d + 1) ** T
        row = {
            "d": d, "T": T, "G": G, "Ed": Ed,
            "vmem_block_edges_shared": vmem_block_edges(d, T),
            "vmem_block_edges_group": vmem_block_edges(d, T, G=G),
            "supported_shared": pallas_group_supported(
                d, T, Ed, G, per_group_a=False),
            "supported_group_a": pallas_group_supported(
                d, T, Ed, G, per_group_a=True),
        }
        # model audit: the group-resident fixed term must be linear in G
        row["group_a_fixed_bytes"] = 4 * G * K * K * M
        row["group_a_fits_budget"] = row["group_a_fixed_bytes"] + \
            8 * (K * K * (d + 2) + K * M) * LANE <= VMEM_BUDGET
        assert row["group_a_fits_budget"] == (
            row["vmem_block_edges_group"] >= LANE
        ), f"VMEM model inconsistent at {(d, T, G)}"
        if row["supported_shared"] or row["supported_group_a"]:
            rng = np.random.default_rng(11)
            chi_in = jnp.asarray(rng.random((G, Ed, d, K, K)), jnp.float32)
            A = jnp.asarray(rng.random((K, K, M)), jnp.float32)
            chi_old = jnp.asarray(rng.random((G, Ed, K, K)), jnp.float32)
        if row["supported_shared"]:
            tilt1 = jnp.ones((K,), jnp.float32)
            ref = jax.vmap(
                lambda ci, co: class_update(
                    ci, A, tilt1, co, d=d, T=T, K=K, damp=damp, eps_clamp=0.0
                )
            )(chi_in, chi_old)
            got = dp_contract_grouped(
                chi_in, A, chi_old, d=d, T=T, damp=damp)
            rel = float(jnp.max(
                jnp.abs(got - ref) / jnp.maximum(jnp.abs(ref), 1e-30)))
            one = dp_contract_grouped(
                chi_in[:1], A, chi_old[:1], d=d, T=T, damp=damp)
            row.update(
                shared_max_rel_err=rel,
                shared_ok=bool(rel < 1e-3),
                g1_bit_equal=bool(jnp.array_equal(got[0], one[0])),
            )
        if row["supported_group_a"]:
            tilts = jnp.asarray(
                np.random.default_rng(12).random((G, K)) + 0.5, jnp.float32)
            a_stack = A[None] * tilts[:, :, None, None]
            refg = jax.vmap(
                lambda ci, co, tl: class_update(
                    ci, A, tl, co, d=d, T=T, K=K, damp=damp, eps_clamp=0.0
                )
            )(chi_in, chi_old, tilts)
            gotg = dp_contract_grouped(
                chi_in, a_stack, chi_old, d=d, T=T, damp=damp)
            relg = float(jnp.max(
                jnp.abs(gotg - refg) / jnp.maximum(jnp.abs(refg), 1e-30)))
            oneg = dp_contract_grouped(
                chi_in[:1], a_stack[:1], chi_old[:1], d=d, T=T, damp=damp)
            row.update(
                group_a_max_rel_err=relg,
                group_a_ok=bool(relg < 1e-3),
                group_a_g1_bit_equal=bool(jnp.array_equal(gotg[0], oneg[0])),
            )
        out.append(row)
        print("group_equiv", row, flush=True)
    return out


def grouped_timing():
    """XLA vmapped class_update vs the grouped kernel at driver-realistic
    (d, T, G, Ed) points — the number the grouped default paths ship."""
    rows = []
    for d, T, G, Ed in [(3, 2, 8, 4096), (3, 2, 8, 32768), (4, 2, 8, 8192),
                        (3, 3, 8, 8192), (3, 2, 32, 8192)]:
        if not pallas_group_supported(d, T, Ed, G, per_group_a=True):
            rows.append({"d": d, "T": T, "G": G, "Ed": Ed,
                         "supported": False})
            continue
        K, M = 2**T, (d + 1) ** T
        rng = np.random.default_rng(13)
        chi_in = jnp.asarray(rng.random((G, Ed, d, K, K)), jnp.float32)
        A = jnp.asarray(rng.random((K, K, M)), jnp.float32)
        chi_old = jnp.asarray(rng.random((G, Ed, K, K)), jnp.float32)
        tilts = jnp.asarray(rng.random((G, K)) + 0.5, jnp.float32)
        a_stack = A[None] * tilts[:, :, None, None]

        def xla_fn(ci, a, co):
            return jax.vmap(
                lambda c1, c2, tl: class_update(
                    c1, A, tl, c2, d=d, T=T, K=K, damp=0.3, eps_clamp=0.0
                )
            )(ci, co, tilts)

        pal = partial(dp_contract_grouped, d=d, T=T, damp=0.3, eps_clamp=0.0)
        t_x = _time(jax.jit(xla_fn), chi_in, a_stack, chi_old, iters=5)
        t_p = _time(pal, chi_in, a_stack, chi_old, iters=5)
        row = {
            "d": d, "T": T, "G": G, "Ed": Ed, "supported": True,
            "xla_us": round(t_x * 1e6, 1),
            "pallas_us": round(t_p * 1e6, 1),
            "speedup": round(t_x / t_p, 2),
        }
        rows.append(row)
        print("group_time", row, flush=True)
    return rows


def sweep_equivalence():
    """Full make_sweep Pallas-vs-XLA on the chip (ER ragged + biased RRG)."""
    res = {}
    g = erdos_renyi_graph(500, 3.0 / 499, seed=3)
    data = BDCMData(g, p=1, c=1)
    sw_x = make_sweep(data, damp=0.2, use_pallas=False)
    sw_p = make_sweep(data, damp=0.2, use_pallas=True)
    chi = data.init_messages(seed=0)
    lam = jnp.float32(0.4)
    cx, cp = chi, chi
    for _ in range(3):
        cx, cp = sw_x(cx, lam), sw_p(cp, lam)
    res["er_sweep_max_abs_err"] = float(jnp.max(jnp.abs(cx - cp)))

    g = random_regular_graph(300, 4, seed=1)
    data = BDCMData(g, p=1, c=1)
    kw = dict(damp=0.4, mask_invalid_src=False, with_bias=True)
    sw_x = make_sweep(data, use_pallas=False, **kw)
    sw_p = make_sweep(data, use_pallas=True, **kw)
    rng = np.random.default_rng(0)
    chi = data.init_messages(seed=5)
    bias = jnp.asarray(rng.random((2 * data.num_edges, data.K)), jnp.float32)
    lam = jnp.float32(25.0)
    res["rrg_bias_sweep_max_abs_err"] = float(
        jnp.max(jnp.abs(sw_x(chi, lam, bias) - sw_p(chi, lam, bias)))
    )
    print("sweep_equiv", res, flush=True)
    return res


def _time(fn, chi_in, A, chi_old, iters=10):
    """Chained timing: each call consumes the previous output (the device
    cannot skip work), and the epilogue reads a scalar back to the host —
    a sync that holds even where the tunneled platform's
    ``block_until_ready`` returns early on large buffers (observed: timings
    collapse to ~18 µs dispatch overhead after a >64 MB execution)."""
    out = fn(chi_in, A, chi_old)
    float(out.sum())
    best = float("inf")
    for _ in range(2):
        out = chi_old
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(chi_in, A, out)
        float(out.sum())
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def timing():
    rows = []
    for d, T in TIMING_GRID_DT:
        for Ed in TIMING_GRID_ED:
            if not pallas_supported(d, T, Ed):
                rows.append({"d": d, "T": T, "Ed": Ed, "supported": False})
                continue
            chi_in, A, chi_old = _inputs(d, T, Ed)
            xla = jax.jit(partial(_xla_ref, d=d, T=T, damp=0.3, eps=0.0))
            pal = partial(dp_contract, d=d, T=T, damp=0.3, eps_clamp=0.0)
            t_x = _time(xla, chi_in, A, chi_old)
            t_p = _time(pal, chi_in, A, chi_old)
            row = {
                "d": d,
                "T": T,
                "Ed": Ed,
                "supported": True,
                "xla_us": round(t_x * 1e6, 1),
                "pallas_us": round(t_p * 1e6, 1),
                "speedup": round(t_x / t_p, 2),
            }
            rows.append(row)
            print("time", row, flush=True)
    return rows


def packed_equivalence():
    """Compiled-mode bit-parity of the per-row-DMA packed dynamics kernel
    (graphdyn.ops.pallas_packed) vs the XLA packed kernel on the real chip —
    the interpret-mode tests prove the math; this proves the Mosaic
    lowering (DMA ring, SMEM index reads) too."""
    from graphdyn.ops.packed import pack_spins, packed_rollout
    from graphdyn.ops.pallas_packed import pallas_packed_rollout

    rows = []
    for d, rule, n, R in [(3, "majority", 4096, 128), (5, "minority", 2048, 64),
                          (3, "majority", 1000, 32)]:   # pad-row path
        g = random_regular_graph(n, d, seed=11)
        rng = np.random.default_rng(4)
        sp = jnp.asarray(pack_spins(
            (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
        ))
        ref = packed_rollout(jnp.asarray(g.nbr), jnp.asarray(g.deg), sp, 5, rule)
        out = pallas_packed_rollout(jnp.asarray(g.nbr), g.deg, sp, 5, rule)
        rows.append({
            "d": d, "rule": rule, "n": n, "R": R,
            "bit_equal": bool(jnp.array_equal(ref, out)),
        })
    return rows


def packed_general_equivalence():
    """Compiled-mode bit-parity of the GENERAL-shapes per-row-DMA kernel
    (ragged/even degrees — `pallas_packed_rollout_general`): the variant the
    tunnel's remote-compile helper returned HTTP 500 on in the r04 window
    (helper-subprocess crash, not a Mosaic lowering error). Each case runs
    independently with the error text captured, so a recurring 500 leaves a
    pinned repro in PALLAS_TPU.json instead of killing the validate run."""
    from graphdyn.ops.packed import pack_spins, packed_rollout
    from graphdyn.ops.pallas_packed import pallas_packed_rollout_general

    rows = []
    for tag, g, rule, tie in [
        ("even_uniform_d4", random_regular_graph(2048, 4, seed=3),
         "majority", "stay"),
        ("ragged_er", erdos_renyi_graph(2048, 6.0 / 2048, seed=5),
         "majority", "change"),
        ("ragged_er_minority", erdos_renyi_graph(1024, 4.0 / 1024, seed=6),
         "minority", "stay"),
    ]:
        R = 64
        rng = np.random.default_rng(9)
        sp = jnp.asarray(pack_spins(
            (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
        ))
        row = {"case": tag, "n": g.n, "rule": rule, "tie": tie}
        try:
            ref = packed_rollout(
                jnp.asarray(g.nbr), jnp.asarray(g.deg), sp, 5, rule, tie)
            out = pallas_packed_rollout_general(
                jnp.asarray(g.nbr), np.asarray(g.deg), sp, 5, rule, tie)
            row["bit_equal"] = bool(jnp.array_equal(ref, out))
        except Exception as e:  # noqa: BLE001 — pin the repro, keep going
            row["error"] = str(e)[:500]
        rows.append(row)
    return rows


def main():
    info = {
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "jax": jax.__version__,
    }
    doc = {
        "info": info,
        "equivalence": equivalence(),
        "grouped_equivalence": grouped_equivalence(),
        "sweep_equivalence": sweep_equivalence(),
        "packed_equivalence": packed_equivalence(),
        "packed_general_equivalence": packed_general_equivalence(),
        "timing": timing(),
        "grouped_timing": grouped_timing(),
    }
    write_json_atomic("PALLAS_TPU.json", doc, indent=1)
    print(json.dumps(info))
    print("WROTE PALLAS_TPU.json")


if __name__ == "__main__":
    main()
