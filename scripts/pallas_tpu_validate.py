"""On-TPU validation + timing of the fused Pallas BDCM kernel.

Runs the test_pallas equivalence matrix in compiled (non-interpret) mode on
the real chip, then times XLA class_update vs Pallas dp_contract across a
(d, T, Ed) grid to replace the `pallas_supported` guess with measured
crossovers. Emits one JSON document (stdout + PALLAS_TPU.json) consumed by
PALLAS_TPU.md.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python scripts/pallas_tpu_validate.py
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from graphdyn.utils.platform import apply_force_platform

apply_force_platform()

import numpy as np

import jax
import jax.numpy as jnp

from graphdyn.graphs import erdos_renyi_graph, random_regular_graph
from graphdyn.utils.io import write_json_atomic
from graphdyn.ops.bdcm import BDCMData, class_update, make_sweep
from graphdyn.ops.pallas_bdcm import dp_contract, pallas_supported, vmem_block_edges

EQUIV_MATRIX = [(1, 2), (2, 2), (3, 2), (4, 2), (5, 2), (6, 2), (8, 2), (3, 3), (4, 3), (2, 4)]
TIMING_GRID_DT = [(3, 2), (4, 2), (5, 2), (3, 3), (4, 3), (2, 4)]
TIMING_GRID_ED = [512, 4096, 32768, 131072]


def _inputs(d, T, Ed, seed=7):
    rng = np.random.default_rng(seed)
    K = 2**T
    M = (d + 1) ** T
    chi_in = jnp.asarray(rng.random((Ed, d, K, K)), jnp.float32)
    A = jnp.asarray(rng.random((K, K, M)), jnp.float32)
    chi_old = jnp.asarray(rng.random((Ed, K, K)), jnp.float32)
    return chi_in, A, chi_old


def _xla_ref(chi_in, A, chi_old, d, T, damp, eps):
    K = 2**T
    tilt = jnp.ones((K,), jnp.float32)
    return class_update(
        chi_in, A, tilt, chi_old, d=d, T=T, K=K, damp=damp, eps_clamp=eps
    )


def equivalence():
    out = []
    damp, eps = 0.3, 0.0
    for d, T in EQUIV_MATRIX:
        Ed = 1000
        supported = pallas_supported(d, T, Ed)
        row = {
            "d": d,
            "T": T,
            "Ed": Ed,
            "supported": supported,
            "vmem_block_edges": vmem_block_edges(d, T),
        }
        if supported:
            chi_in, A, chi_old = _inputs(d, T, Ed)
            ref = _xla_ref(chi_in, A, chi_old, d, T, damp, eps)
            got = dp_contract(chi_in, A, chi_old, d=d, T=T, damp=damp, eps_clamp=eps)
            err = float(jnp.max(jnp.abs(got - ref)))
            rel = float(jnp.max(jnp.abs(got - ref) / jnp.maximum(jnp.abs(ref), 1e-30)))
            row.update(max_abs_err=err, max_rel_err=rel, ok=bool(err < 1e-3))
        out.append(row)
        print("equiv", row, flush=True)
    return out


def sweep_equivalence():
    """Full make_sweep Pallas-vs-XLA on the chip (ER ragged + biased RRG)."""
    res = {}
    g = erdos_renyi_graph(500, 3.0 / 499, seed=3)
    data = BDCMData(g, p=1, c=1)
    sw_x = make_sweep(data, damp=0.2, use_pallas=False)
    sw_p = make_sweep(data, damp=0.2, use_pallas=True)
    chi = data.init_messages(seed=0)
    lam = jnp.float32(0.4)
    cx, cp = chi, chi
    for _ in range(3):
        cx, cp = sw_x(cx, lam), sw_p(cp, lam)
    res["er_sweep_max_abs_err"] = float(jnp.max(jnp.abs(cx - cp)))

    g = random_regular_graph(300, 4, seed=1)
    data = BDCMData(g, p=1, c=1)
    kw = dict(damp=0.4, mask_invalid_src=False, with_bias=True)
    sw_x = make_sweep(data, use_pallas=False, **kw)
    sw_p = make_sweep(data, use_pallas=True, **kw)
    rng = np.random.default_rng(0)
    chi = data.init_messages(seed=5)
    bias = jnp.asarray(rng.random((2 * data.num_edges, data.K)), jnp.float32)
    lam = jnp.float32(25.0)
    res["rrg_bias_sweep_max_abs_err"] = float(
        jnp.max(jnp.abs(sw_x(chi, lam, bias) - sw_p(chi, lam, bias)))
    )
    print("sweep_equiv", res, flush=True)
    return res


def _time(fn, chi_in, A, chi_old, iters=10):
    """Chained timing: each call consumes the previous output (the device
    cannot skip work), and the epilogue reads a scalar back to the host —
    a sync that holds even where the tunneled platform's
    ``block_until_ready`` returns early on large buffers (observed: timings
    collapse to ~18 µs dispatch overhead after a >64 MB execution)."""
    out = fn(chi_in, A, chi_old)
    float(out.sum())
    best = float("inf")
    for _ in range(2):
        out = chi_old
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(chi_in, A, out)
        float(out.sum())
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def timing():
    rows = []
    for d, T in TIMING_GRID_DT:
        for Ed in TIMING_GRID_ED:
            if not pallas_supported(d, T, Ed):
                rows.append({"d": d, "T": T, "Ed": Ed, "supported": False})
                continue
            chi_in, A, chi_old = _inputs(d, T, Ed)
            xla = jax.jit(partial(_xla_ref, d=d, T=T, damp=0.3, eps=0.0))
            pal = partial(dp_contract, d=d, T=T, damp=0.3, eps_clamp=0.0)
            t_x = _time(xla, chi_in, A, chi_old)
            t_p = _time(pal, chi_in, A, chi_old)
            row = {
                "d": d,
                "T": T,
                "Ed": Ed,
                "supported": True,
                "xla_us": round(t_x * 1e6, 1),
                "pallas_us": round(t_p * 1e6, 1),
                "speedup": round(t_x / t_p, 2),
            }
            rows.append(row)
            print("time", row, flush=True)
    return rows


def packed_equivalence():
    """Compiled-mode bit-parity of the per-row-DMA packed dynamics kernel
    (graphdyn.ops.pallas_packed) vs the XLA packed kernel on the real chip —
    the interpret-mode tests prove the math; this proves the Mosaic
    lowering (DMA ring, SMEM index reads) too."""
    from graphdyn.ops.packed import pack_spins, packed_rollout
    from graphdyn.ops.pallas_packed import pallas_packed_rollout

    rows = []
    for d, rule, n, R in [(3, "majority", 4096, 128), (5, "minority", 2048, 64),
                          (3, "majority", 1000, 32)]:   # pad-row path
        g = random_regular_graph(n, d, seed=11)
        rng = np.random.default_rng(4)
        sp = jnp.asarray(pack_spins(
            (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
        ))
        ref = packed_rollout(jnp.asarray(g.nbr), jnp.asarray(g.deg), sp, 5, rule)
        out = pallas_packed_rollout(jnp.asarray(g.nbr), g.deg, sp, 5, rule)
        rows.append({
            "d": d, "rule": rule, "n": n, "R": R,
            "bit_equal": bool(jnp.array_equal(ref, out)),
        })
    return rows


def packed_general_equivalence():
    """Compiled-mode bit-parity of the GENERAL-shapes per-row-DMA kernel
    (ragged/even degrees — `pallas_packed_rollout_general`): the variant the
    tunnel's remote-compile helper returned HTTP 500 on in the r04 window
    (helper-subprocess crash, not a Mosaic lowering error). Each case runs
    independently with the error text captured, so a recurring 500 leaves a
    pinned repro in PALLAS_TPU.json instead of killing the validate run."""
    from graphdyn.ops.packed import pack_spins, packed_rollout
    from graphdyn.ops.pallas_packed import pallas_packed_rollout_general

    rows = []
    for tag, g, rule, tie in [
        ("even_uniform_d4", random_regular_graph(2048, 4, seed=3),
         "majority", "stay"),
        ("ragged_er", erdos_renyi_graph(2048, 6.0 / 2048, seed=5),
         "majority", "change"),
        ("ragged_er_minority", erdos_renyi_graph(1024, 4.0 / 1024, seed=6),
         "minority", "stay"),
    ]:
        R = 64
        rng = np.random.default_rng(9)
        sp = jnp.asarray(pack_spins(
            (2 * rng.integers(0, 2, size=(R, g.n)) - 1).astype(np.int8)
        ))
        row = {"case": tag, "n": g.n, "rule": rule, "tie": tie}
        try:
            ref = packed_rollout(
                jnp.asarray(g.nbr), jnp.asarray(g.deg), sp, 5, rule, tie)
            out = pallas_packed_rollout_general(
                jnp.asarray(g.nbr), np.asarray(g.deg), sp, 5, rule, tie)
            row["bit_equal"] = bool(jnp.array_equal(ref, out))
        except Exception as e:  # noqa: BLE001 — pin the repro, keep going
            row["error"] = str(e)[:500]
        rows.append(row)
    return rows


def main():
    info = {
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "jax": jax.__version__,
    }
    doc = {
        "info": info,
        "equivalence": equivalence(),
        "sweep_equivalence": sweep_equivalence(),
        "packed_equivalence": packed_equivalence(),
        "packed_general_equivalence": packed_general_equivalence(),
        "timing": timing(),
    }
    write_json_atomic("PALLAS_TPU.json", doc, indent=1)
    print(json.dumps(info))
    print("WROTE PALLAS_TPU.json")


if __name__ == "__main__":
    main()
