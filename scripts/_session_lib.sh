# Shared helpers for the chip session scripts (sourced, not executed).
#
# Refires reuse the same outdir, so every stage must be idempotent: skip
# when the artifact it would produce already holds good data, and never
# truncate a good artifact just to re-measure it. The config aggregator
# resumes natively; these helpers give the other stages the same property.

# ROUND_DOC: the benchmark doc all sessions merge into (one place to bump
# per round instead of editing every session script).
ROUND_DOC="${ROUND_DOC:-BENCH_CONFIGS_r05.json}"

# json_ok FILE — file exists and parses as JSON
json_ok() {
    python - "$1" >/dev/null 2>&1 <<'EOF'
import json, sys
json.load(open(sys.argv[1]))
EOF
}

# headline_ok FILE — chip_doc_ok AND carries a real rate (a failed bench
# emits an error JSON with value 0.0; a wedged-relay bench may emit a
# nonzero CPU-fallback row — a refire into a recovered relay must replace
# both). One chip-contract (chip_doc_ok below) + the value check.
headline_ok() {
    chip_doc_ok "$1" && python - "$1" >/dev/null 2>&1 <<'EOF'
import json, sys
assert json.load(open(sys.argv[1])).get("value", 0) > 0
EOF
}

# rows_ok FILE — a JSONL artifact with at least one row
rows_ok() { [ -s "$1" ]; }

# chip_doc_ok FILE — a JSON artifact that parses AND records a chip backend
# with no fallback label (a CPU-fallback capture must not block a refire
# from replacing it with chip data — same contract as headline_ok)
chip_doc_ok() {
    python - "$1" >/dev/null 2>&1 <<'EOF'
import json, sys
d = json.load(open(sys.argv[1]))
assert d.get("backend") in ("tpu", "axon")
assert "relay" not in d
EOF
}

# collect_round OUTDIR TAG — merge the session dir into the round doc
# (idempotent; fired near round end the driver commits the tree as-is,
# with nobody around to run the collector by hand)
collect_round() {
    echo "[$2] merging artifacts into $ROUND_DOC ..." >&2
    python scripts/collect_tpu_session.py "$1" "$ROUND_DOC" >&2
    echo "[$2] collect rc=$?" >&2
}
