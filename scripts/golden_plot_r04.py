#!/usr/bin/env python3
"""Render the golden-curve comparison figure from GOLDEN_r04.json.

The notebook exists to produce the tilted-entropy curve s(m_init)
(`code/README.md:1`, `ER_BDCM_entropy.ipynb:18-46`); this figure overlays
the framework's float64 curves (8 ER instances, networkx sampler,
notebook-exact config) with the reference's ten stored (m_init, ent1)
triples — the visual form of the GOLDEN_r04.json claim that the reference
run is statistically indistinguishable from the framework's ensemble.

Two identities only: the framework instance curves (one muted blue, they
are an ensemble, not eight series) and the reference points (warm orange,
distinct marker). Single axis pair, recessive grid, direct legend.
"""

import json
import sys

import numpy as np

FRAMEWORK = "#4269d0"   # muted blue — ensemble curves
REFERENCE = "#e4632d"   # warm orange — the ten stored triples


def main(src="GOLDEN_r04.json", out="golden_curve_r04.png"):
    import matplotlib

    matplotlib.use("Agg", force=True)
    import matplotlib.pyplot as plt

    with open(src) as f:
        art = json.load(f)

    fig, ax = plt.subplots(figsize=(6.0, 4.2), dpi=150)
    for i, row in enumerate(art["per_seed"]):
        m = np.asarray(row["m_init"], float)
        s = np.asarray(row["ent1"], float)
        # mask (don't drop) degraded points — non-finite OR far below the
        # entropy floor — so the line BREAKS there instead of bridging a
        # gap with fabricated segments
        bad = ~(np.isfinite(m) & np.isfinite(s)) | (s < -0.2)
        m, s = m.copy(), s.copy()
        m[bad] = np.nan
        s[bad] = np.nan
        ax.plot(
            m, s, color=FRAMEWORK, lw=1.2, alpha=0.55,
            label=(
                f"graphdyn float64 ({len(art['per_seed'])} instances)"
                if i == 0 else None
            ),
            zorder=2,
        )
    golden = art["spread_at_golden_lambdas"]
    gm = [v["golden_m_init"] for v in golden.values()]
    ge = [v["golden_ent1"] for v in golden.values()]
    ax.plot(
        gm, ge, ls="none", marker="o", ms=6, mfc=REFERENCE, mec="white",
        mew=1.0, label="reference stored run (ipynb:18-46)", zorder=3,
    )
    ax.set_xlabel(r"$m_{\mathrm{init}}$")
    ax.set_ylabel(r"$s(m_{\mathrm{init}}) = \phi + \lambda\, m_{\mathrm{init}}$")
    ax.set_title(
        "BDCM tilted entropy, ER deg=1.0, n=1000, p=c=1 (float64)",
        fontsize=10,
    )
    ax.axhline(0.0, color="0.8", lw=0.8, zorder=1)
    ax.grid(True, color="0.92", lw=0.6, zorder=0)
    for spine in ("top", "right"):
        ax.spines[spine].set_visible(False)
    ax.legend(frameon=False, fontsize=8, loc="upper left")
    fig.tight_layout()
    fig.savefig(out)
    print(f"wrote {out}")


if __name__ == "__main__":
    main(*sys.argv[1:])
