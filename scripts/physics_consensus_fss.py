#!/usr/bin/env python3
"""Finite-size scaling of the opinion-consensus transition.

The m(0)→consensus curve sharpens with N. Two candidate scalings:

1. noise-driven: x = m(0)·√N (bias vs the √N magnetization noise of a
   random init) — curves at different N collapse iff the transition point
   itself sits at the noise scale, m_c ~ N^(-1/2) → 0;
2. finite threshold: the transition sits at a FIXED critical bias m_c > 0
   and only its WIDTH shrinks like N^(-1/2) — then the collapsing variable
   is (m(0) − m_c)·√N, and naive m(0)·√N does NOT collapse.

Measured (2026-07-31, N = 1e4/3.16e4/1e5, c = 6): the half-consensus point
lands at m(0) ≈ 0.010 at ALL three sizes — the ER-c=6 majority transition
has a finite critical bias, so (2) is the right picture. The plot shows
raw curves (sharpening around a fixed m_c), the failed naive collapse, and
the (m(0) − m_c)·√N collapse with per-N interpolated m_c. The m0=0 tail of
the smallest N sits high for a separate, budgeted reason: unbiased
fluctuation-driven consensus within max_steps, a finite-TIME effect.

Usage:
  python scripts/physics_consensus_fss.py OUT_JSON [OUT_PNG]
      [--instances K] [--replot]

--replot renders from an existing OUT_JSON without re-simulating. Same
wedge protection as the other capture scripts (probe + init watchdog +
labeled CPU fallback).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

import benchmarks.common  # noqa: F401 — repo root + platform forcing

# shared scaled grid: x = m(0)·√N, from unbiased through deep in the
# consensus phase (x≈3 is the N=1e5 transition midpoint seen in
# er_consensus_r05.json: m0=0.01 ⇒ x=3.16 ⇒ fraction ≈ 0.54)
X_GRID = (0.0, 0.5, 1.0, 1.5, 2.0, 2.5, 3.0, 3.5, 4.0, 5.0, 6.5)
N_GRID = (10_000, 31_623, 100_000)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("out_json")
    ap.add_argument("out_png", nargs="?", default=None)
    ap.add_argument("--instances", type=int, default=2)
    ap.add_argument("--replicas", type=int, default=256)
    ap.add_argument("--max-steps", type=int, default=2000)
    ap.add_argument("--replot", action="store_true",
                    help="render OUT_PNG from an existing OUT_JSON")
    a = ap.parse_args()

    if a.replot:
        if not a.out_png:
            ap.error("--replot requires OUT_PNG (it only renders)")
        with open(a.out_json) as f:
            doc = json.load(f)
    else:
        from benchmarks.common import guarded_capture_init

        relay_note = guarded_capture_init()
        import jax

        from graphdyn.models.consensus import consensus_curve_ensemble

        t0 = time.time()
        curves = []
        for n in N_GRID:
            m0s = tuple(x / n ** 0.5 for x in X_GRID)
            per_seed, agg = consensus_curve_ensemble(
                n, a.replicas, m0s, a.max_steps,
                graph_seeds=tuple(range(a.instances)),
            )
            for row, x in zip(agg, X_GRID):
                row["x"] = x
            curves.append({"n": n, "aggregate": agg, "per_seed": per_seed})
            print(f"N={n}: " + " ".join(
                f"x={x:g}:{r['consensus_fraction_mean']:.2f}"
                for x, r in zip(X_GRID, agg)), flush=True)

        doc = {
            "what": ("finite-size scaling of the ER-majority consensus "
                     "transition: finite critical bias m_c with "
                     "width ~ N^(-1/2); naive m(0)·√N does NOT collapse"),
            "x_grid": list(X_GRID),
            "n_grid": list(N_GRID),
            "replicas": a.replicas,
            "instances": a.instances,
            "max_steps": a.max_steps,
            "backend": jax.default_backend(),
            "elapsed_s": round(time.time() - t0, 1),
            "curves": curves,
            **({"relay": relay_note} if relay_note else {}),
        }

    # half-consensus point per N — the measured m_c(N); its N-independence
    # is the headline finding (one shared crossing definition:
    # graphdyn.models.consensus.m_half)
    from graphdyn.models.consensus import m_half

    doc["m_half_by_n"] = {
        str(cv["n"]): m_half(cv["aggregate"]) for cv in doc["curves"]
    }
    if not a.replot:
        # atomic, and --replot never rewrites the measured artifact at all
        tmp = a.out_json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, a.out_json)
        print(f"wrote {a.out_json} (backend={doc['backend']}, "
              f"m_half={doc['m_half_by_n']})")

    if a.out_png:
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        fig, (ax1, ax2, ax3) = plt.subplots(1, 3, figsize=(12.6, 3.7),
                                            dpi=120)
        for cv in doc["curves"]:
            n = cv["n"]
            agg = cv["aggregate"]
            fr = [r["consensus_fraction_mean"] for r in agg]
            err = [r["consensus_fraction_std"] or 0.0 for r in agg]
            m0s = [r["m0"] for r in agg]
            mc = doc["m_half_by_n"][str(n)]
            lbl = f"N={n:,}"
            ax1.errorbar(m0s, fr, yerr=err, fmt="o-", ms=3.5, lw=1.1,
                         capsize=2, label=lbl)
            ax2.errorbar([r["x"] for r in agg], fr, yerr=err, fmt="o-",
                         ms=3.5, lw=1.1, capsize=2, label=lbl)
            if mc is not None:
                ax3.errorbar([(m - mc) * n ** 0.5 for m in m0s], fr,
                             yerr=err, fmt="o-", ms=3.5, lw=1.1, capsize=2,
                             label=f"{lbl}, $m_c$={mc:.4f}")
            else:
                # no crossing on the grid: say so instead of silently
                # shrinking the collapse panel
                ax3.plot([], [], " ", label=f"{lbl}: $m_c$ below grid — omitted")
        mcs = [v for v in doc["m_half_by_n"].values() if v is not None]
        ax1.set_xlabel("initial magnetization m(0)")
        ax1.set_ylabel("consensus fraction")
        ax1.set_title(f"raw: fixed $m_c \\approx {np_mean(mcs):.3f}$, "
                      "width shrinks", fontsize=9)
        ax1.legend(frameon=False, fontsize=7)
        ax2.set_xlabel(r"m(0)·$\sqrt{N}$")
        ax2.set_title("naive noise scaling: NO collapse\n"
                      r"($m_c$ is finite, not ~$N^{-1/2}$)", fontsize=9)
        ax2.legend(frameon=False, fontsize=7)
        ax3.set_xlabel(r"(m(0) − $m_c$)·$\sqrt{N}$")
        ax3.set_title("width scaling: collapse about $m_c$", fontsize=9)
        ax3.legend(frameon=False, fontsize=7)
        fig.tight_layout()
        fig.savefig(a.out_png)
        print(f"wrote {a.out_png}")
    return 0


def np_mean(xs):
    return sum(xs) / len(xs) if xs else float("nan")


if __name__ == "__main__":
    sys.exit(main())
