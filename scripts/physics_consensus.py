#!/usr/bin/env python3
"""ER-majority opinion-consensus physics: consensus fraction and
first-passage time vs initial magnetization m(0).

The thesis objective (SURVEY.md §0.3) is finding initializations that flow
to opinion consensus; the reference's entropy curves (`ER_BDCM_entropy.ipynb`)
quantify the attractor landscape those initializations must escape. This
script measures the forward-dynamics side of that story on the BASELINE
config-3 ensemble — ER G(N, 6/N), majority rule, packed replicas — and
writes a json + png artifact (VERDICT r04 next-step 5).

Usage:
  python scripts/physics_consensus.py OUT_JSON [OUT_PNG] [--full]

CPU smoke by default shapes; --full is the BASELINE N=1e5, R=512 shape
(chip-sized but CPU-feasible). Platform selection via GRAPHDYN_FORCE_PLATFORM
(applied by benchmarks.common import).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import benchmarks.common  # noqa: F401 — repo root + platform forcing
from graphdyn.utils.io import write_json_atomic

M0_GRID = (0.0, 0.01, 0.02, 0.03, 0.05, 0.07, 0.1, 0.15, 0.2, 0.3)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("out_json")
    ap.add_argument("out_png", nargs="?", default=None)
    ap.add_argument("--full", action="store_true")
    ap.add_argument(
        "--instances", type=int, default=None,
        help="graph instances (default: 3 with --full, 1 smoke); lower it "
             "when the stage budget is tight — there is no per-instance "
             "resume, so a timeout loses the whole sweep",
    )
    a = ap.parse_args()

    # the shared wedge protection (benchmarks.common.guarded_capture_init):
    # an unforced run on a wedged relay would otherwise hang forever in jax
    # init and write NO artifact
    from benchmarks.common import guarded_capture_init

    relay_note = guarded_capture_init()

    from graphdyn.models.consensus import (
        consensus_curve_ensemble,
        consensus_ensemble_doc,
    )

    # --full: three graph instances for error bars (the same instance-spread
    # discipline as the entropy golden anchors); smoke: one
    n, R, max_steps, seeds = ((100_000, 512, 2000, (0, 1, 2)) if a.full
                              else (20_000, 128, 500, (0,)))
    if a.instances is not None:
        seeds = tuple(range(a.instances))
    t0 = time.time()

    def progress(seed, pt):
        print(f"seed={seed} m0={pt['m0']:g}: "
              f"consensus={pt['consensus_fraction']:.3f} "
              f"strict={pt['strict_fraction']:.3f} "
              f"steps={pt['mean_steps_to_consensus']} "
              f"|m_f|={pt['mean_abs_m_final']:.3f}", flush=True)

    per_seed, aggregate = consensus_curve_ensemble(
        n, R, M0_GRID, max_steps, graph_seeds=seeds, chunk=10,
        progress=progress,
    )

    doc = consensus_ensemble_doc(
        n, per_seed, aggregate,
        elapsed_s=round(time.time() - t0, 1),
        **({"relay": relay_note} if relay_note else {}),
    )
    write_json_atomic(a.out_json, doc, indent=1)
    print(f"wrote {a.out_json} (backend={doc['backend']}, "
          f"{len(per_seed)} instances)")

    if a.out_png:
        from graphdyn.plotting import plot_consensus_curve

        plot_consensus_curve(
            aggregate,
            title=f"ER c=6, N={n}, R={R}, majority, "
                  f"{len(per_seed)} instances",
            save_path=a.out_png,
        )
        print(f"wrote {a.out_png}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
