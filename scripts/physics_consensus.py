#!/usr/bin/env python3
"""ER-majority opinion-consensus physics: consensus fraction and
first-passage time vs initial magnetization m(0).

The thesis objective (SURVEY.md §0.3) is finding initializations that flow
to opinion consensus; the reference's entropy curves (`ER_BDCM_entropy.ipynb`)
quantify the attractor landscape those initializations must escape. This
script measures the forward-dynamics side of that story on the BASELINE
config-3 ensemble — ER G(N, 6/N), majority rule, packed replicas — and
writes a json + png artifact (VERDICT r04 next-step 5).

Usage:
  python scripts/physics_consensus.py OUT_JSON [OUT_PNG] [--full]

CPU smoke by default shapes; --full is the BASELINE N=1e5, R=512 shape
(chip-sized but CPU-feasible). Platform selection via GRAPHDYN_FORCE_PLATFORM
(applied by benchmarks.common import).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import benchmarks.common  # noqa: F401 — repo root + platform forcing

M0_GRID = (0.0, 0.01, 0.02, 0.03, 0.05, 0.07, 0.1, 0.15, 0.2, 0.3)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("out_json")
    ap.add_argument("out_png", nargs="?", default=None)
    ap.add_argument("--full", action="store_true")
    a = ap.parse_args()

    # the same wedge protection as bench.py: an unforced run on a wedged
    # relay would otherwise hang forever in jax init and write NO artifact.
    # Probe first (a wedged relay hangs in-process init unrecoverably),
    # then arm the watchdog for the probe→init wedge window: on a hang it
    # re-execs this script with CPU forced, and probe_or_cpu_fallback in
    # the re-exec returns the fallback label.
    from benchmarks.common import init_watchdog, probe_or_cpu_fallback

    relay_note = probe_or_cpu_fallback()
    init_done = init_watchdog(
        allow_cpu_fallback=not (os.environ.get("GRAPHDYN_FORCE_PLATFORM")
                                and not os.environ.get("BENCH_CPU_REEXEC")))

    import jax

    jax.devices()
    init_done.set()

    from graphdyn.models.consensus import consensus_curve, er_consensus_ensemble

    n, R, max_steps = (100_000, 512, 2000) if a.full else (20_000, 128, 500)
    g, n_iso, nbr_dev, deg_dev = er_consensus_ensemble(n)
    t0 = time.time()

    def progress(pt):
        print(f"m0={pt['m0']:g}: consensus={pt['consensus_fraction']:.3f} "
              f"strict={pt['strict_fraction']:.3f} "
              f"steps={pt['mean_steps_to_consensus']} "
              f"|m_f|={pt['mean_abs_m_final']:.3f}", flush=True)

    rows = consensus_curve(g, R, M0_GRID, max_steps, chunk=10,
                           nbr_dev=nbr_dev, deg_dev=deg_dev,
                           progress=progress)

    from graphdyn.models.consensus import consensus_doc

    doc = consensus_doc(
        g, n_iso, rows,
        elapsed_s=round(time.time() - t0, 1),
        **({"relay": relay_note} if relay_note else {}),
    )
    with open(a.out_json, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {a.out_json} (backend={doc['backend']})")

    if a.out_png:
        from graphdyn.plotting import plot_consensus_curve

        plot_consensus_curve(
            rows, title=f"ER c=6, N={g.n}, R={R}, majority",
            save_path=a.out_png,
        )
        print(f"wrote {a.out_png}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
