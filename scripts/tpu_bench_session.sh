#!/bin/bash
# One-shot TPU benchmark session: run everything that needs the real chip and
# collect artifacts. Fire this as soon as the tunnel is confirmed up (the
# relay wedges unpredictably — front-load chip work):
#
#   bash scripts/tpu_bench_session.sh [outdir]
#
# Produces in <outdir> (default /tmp/tpu_session):
#   bench_headline.json      — bench.py (packed kernel, natural vs BFS order)
#   gather_experiment.jsonl  — fused vs per-slot vs slot-sorted A/B/C
#   configs_tpu.json         — all five BASELINE configs, full scale
#
# Idempotent per stage: a refire into the same outdir skips stages whose
# artifact already holds good data (never truncates good chip data to
# re-measure it) and re-runs only what is missing or failed.
set -u
cd "$(dirname "$0")/.."
. scripts/_session_lib.sh
OUT="${1:-/tmp/tpu_session}"
mkdir -p "$OUT"

if headline_ok "$OUT/bench_headline.json"; then
    echo "[tpu-session] headline bench already captured; skipping" >&2
else
    echo "[tpu-session] headline bench ..." >&2
    timeout 1800 python bench.py > "$OUT/bench_headline.json" 2> "$OUT/bench_headline.err"
    echo "[tpu-session] bench rc=$? $(tail -c 300 "$OUT/bench_headline.json")" >&2
fi

if rows_ok "$OUT/gather_experiment.jsonl"; then
    echo "[tpu-session] gather experiment already captured; skipping" >&2
else
    echo "[tpu-session] gather experiment ..." >&2
    timeout 1800 python scripts/packed_gather_experiment.py \
        > "$OUT/gather_experiment.jsonl" 2> "$OUT/gather_experiment.err"
    echo "[tpu-session] gather rc=$?" >&2
fi

if rows_ok "$OUT/pallas_gather_probe.jsonl"; then
    echo "[tpu-session] pallas gather probe already captured; skipping" >&2
else
    echo "[tpu-session] pallas random-row gather probe ..." >&2
    timeout 1800 python scripts/pallas_gather_probe.py \
        > "$OUT/pallas_gather_probe.jsonl" 2> "$OUT/pallas_gather_probe.err"
    echo "[tpu-session] probe rc=$?" >&2
fi

if json_ok "$OUT/PALLAS_TPU.json"; then
    echo "[tpu-session] pallas validation already captured; skipping" >&2
else
    echo "[tpu-session] pallas on-chip validation (BDCM + packed kernels) ..." >&2
    timeout 1800 python scripts/pallas_tpu_validate.py \
        > "$OUT/pallas_validate.log" 2>&1
    rc=$?
    echo "[tpu-session] pallas validate rc=$rc" >&2
    [ $rc -eq 0 ] && cp -f PALLAS_TPU.json "$OUT/PALLAS_TPU.json"
fi

echo "[tpu-session] five BASELINE configs (full) ..." >&2
# per-config budget x5 must fit inside the outer budget, or the aggregator
# dies before writing --out and every completed config's result is lost.
# --platform axon (the tunneled-TPU plugin): chip-or-hang, never a silent
# CPU fallback. The aggregator resumes completed configs natively.
timeout 9000 python scripts/run_baseline_configs.py \
    --out "$OUT/configs_tpu.json" --full --timeout 1500 --platform axon >&2
echo "[tpu-session] configs rc=$?" >&2

if json_ok "$OUT/physics_tpu.json"; then
    echo "[tpu-session] physics already captured; skipping" >&2
else
    echo "[tpu-session] physics on chip (HPr at reference constants) ..." >&2
    GRAPHDYN_FORCE_PLATFORM=axon timeout 1200 \
        python scripts/physics_r04.py hpr "$OUT/physics_tpu.json" \
        > "$OUT/physics_tpu.log" 2>&1
    echo "[tpu-session] physics rc=$?" >&2
fi

collect_round "$OUT" tpu-session
echo "[tpu-session] done; artifacts in $OUT" >&2
