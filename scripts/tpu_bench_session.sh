#!/bin/bash
# One-shot TPU benchmark session: run everything that needs the real chip and
# collect artifacts. Fire this as soon as the tunnel is confirmed up (the
# relay wedges unpredictably — front-load chip work), ordered by round-5
# evidence value so a mid-session wedge leaves the most important artifacts
# behind:
#   1. headline bench (packed kernel + replica-widening rungs + Pallas A/B)
#   2. Pallas on-chip validation at current HEAD (never yet run compiled)
#   3. five BASELINE configs, full scale (incl. light-cone n=1e4/1e5/1e6
#      scaling, HPr T=3 Pallas-on/off A/B, config-2 torch-divisor ratio,
#      config-3 consensus physics rows)
#   4. ER-majority consensus physics artifact (json + png)
#   5. HPr physics at reference constants
#   6. gather A/B/C + per-row-DMA probe (re-validation of r04 findings)
#
#   bash scripts/tpu_bench_session.sh [outdir]
#
# Idempotent per stage: a refire into the same outdir skips stages whose
# artifact already holds good data (never truncates good chip data to
# re-measure it) and re-runs only what is missing or failed.
set -u
cd "$(dirname "$0")/.."
. scripts/_session_lib.sh
OUT="${1:-/tmp/tpu_session}"
mkdir -p "$OUT"

if headline_ok "$OUT/bench_headline.json"; then
    echo "[tpu-session] headline bench already captured; skipping" >&2
else
    echo "[tpu-session] headline bench ..." >&2
    # short probe budget: the watcher fired because the canary saw UP, so a
    # failing probe here means the relay wedged again — better to fall back
    # fast (headline_ok rejects the fallback row, keeping refires armed)
    BENCH_INIT_BUDGET_S=180 timeout 1800 \
        python bench.py > "$OUT/bench_headline.json" 2> "$OUT/bench_headline.err"
    echo "[tpu-session] bench rc=$? $(tail -c 300 "$OUT/bench_headline.json")" >&2
fi

if json_ok "$OUT/PALLAS_TPU.json"; then
    echo "[tpu-session] pallas validation already captured; skipping" >&2
else
    echo "[tpu-session] pallas on-chip validation (BDCM + packed kernels) ..." >&2
    GRAPHDYN_FORCE_PLATFORM=axon timeout 1800 \
        python scripts/pallas_tpu_validate.py \
        > "$OUT/pallas_validate.log" 2>&1
    rc=$?
    echo "[tpu-session] pallas validate rc=$rc" >&2
    [ $rc -eq 0 ] && cp -f PALLAS_TPU.json "$OUT/PALLAS_TPU.json"
fi

echo "[tpu-session] five BASELINE configs (full) ..." >&2
# per-config budget x5 must fit inside the outer budget, or the aggregator
# dies before writing --out and every completed config's result is lost.
# --platform axon (the tunneled-TPU plugin): chip-or-hang, never a silent
# CPU fallback. The aggregator resumes completed configs natively.
timeout 9000 python scripts/run_baseline_configs.py \
    --out "$OUT/configs_tpu.json" --full --timeout 1500 --platform axon >&2
echo "[tpu-session] configs rc=$?" >&2

if chip_doc_ok "$OUT/consensus_tpu.json"; then
    echo "[tpu-session] consensus physics already captured; skipping" >&2
else
    echo "[tpu-session] ER-majority consensus physics (m0 sweep) ..." >&2
    # 2700 s: --full is a 3-instance sweep (~20 min measured on CPU; far
    # faster on chip, but the budget must cover a slow tunnel — there is
    # no per-instance resume, so a timeout loses the whole sweep)
    GRAPHDYN_FORCE_PLATFORM=axon timeout 2700 \
        python scripts/physics_consensus.py \
        "$OUT/consensus_tpu.json" "$OUT/consensus_tpu.png" --full \
        > "$OUT/consensus_tpu.log" 2>&1
    echo "[tpu-session] consensus rc=$?" >&2
fi

if json_ok "$OUT/physics_tpu.json"; then
    echo "[tpu-session] physics already captured; skipping" >&2
else
    echo "[tpu-session] physics on chip (HPr at reference constants) ..." >&2
    GRAPHDYN_FORCE_PLATFORM=axon timeout 1200 \
        python scripts/physics_r04.py hpr "$OUT/physics_tpu.json" \
        > "$OUT/physics_tpu.log" 2>&1
    echo "[tpu-session] physics rc=$?" >&2
fi

if rows_ok "$OUT/gather_experiment.jsonl"; then
    echo "[tpu-session] gather experiment already captured; skipping" >&2
else
    echo "[tpu-session] gather experiment ..." >&2
    timeout 1200 python scripts/packed_gather_experiment.py \
        > "$OUT/gather_experiment.jsonl" 2> "$OUT/gather_experiment.err"
    echo "[tpu-session] gather rc=$?" >&2
fi

if rows_ok "$OUT/pallas_gather_probe.jsonl"; then
    echo "[tpu-session] pallas gather probe already captured; skipping" >&2
else
    echo "[tpu-session] pallas random-row gather probe ..." >&2
    timeout 1200 python scripts/pallas_gather_probe.py \
        > "$OUT/pallas_gather_probe.jsonl" 2> "$OUT/pallas_gather_probe.err"
    echo "[tpu-session] probe rc=$?" >&2
fi

collect_round "$OUT" tpu-session
echo "[tpu-session] done; artifacts in $OUT" >&2
