#!/usr/bin/env python3
"""BASELINE config 1: SA simulated annealing, d=3 RRG, N=1e4, 32 replicas.

Measures full SA MCMC steps/sec (each step = one candidate rollout over the
whole replica batch + Metropolis update) and compares against the numpy
reference-style chain on the same graph. ``--full`` uses the BASELINE shapes;
the default is a scaled-down smoke size.
"""

import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np

from benchmarks.common import report
from graphdyn.config import DynamicsConfig, SAConfig
from graphdyn.graphs import random_regular_graph
from graphdyn.models.sa import simulated_annealing


def _setup(n, R, steps, device_s0=False):
    """Shared graph + config + injected-stream setup (seed 0).

    ``device_s0`` draws the spin state on device (`benchmarks.common
    .draw_pm1_int8`) instead of host-side — required at n=1e6 where a
    host draw means a 32 MB upload over the tunneled TPU link; the
    proposal/uniform streams stay host-drawn (KB-sized, and they keep
    chains reproducible against the injected-stream tests)."""
    g = random_regular_graph(n, 3, seed=0)
    cfg = SAConfig(dynamics=DynamicsConfig(p=3, c=1))
    rng = np.random.default_rng(0)
    if device_s0:
        from benchmarks.common import draw_pm1_int8

        s0 = draw_pm1_int8(0, (R, g.n))
    else:
        s0 = (2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8)
    proposals = rng.integers(0, n, size=(R, steps)).astype(np.int32)
    uniforms = rng.random(size=(R, steps))
    return g, cfg, s0, proposals, uniforms


def _timed_steady(g, cfg, s0, proposals, uniforms, steps, **kw):
    """Run twice with identical inputs (deterministic chains) and time the
    second call — jit compile and any host-side table build land in the
    warm-up, so the metric measures per-step throughput."""
    for _ in range(2):
        t0 = time.perf_counter()
        simulated_annealing(
            g, cfg, s0=s0, proposals=proposals, uniforms=uniforms,
            max_steps=steps - 1, backend="jax_tpu", **kw,
        )
    return time.perf_counter() - t0


def run(n, R, steps):
    g, cfg, s0, proposals, uniforms = _setup(n, R, steps)

    def timed_steady(**kw):
        return _timed_steady(g, cfg, s0, proposals, uniforms, steps, **kw)

    # device path (one candidate rollout per step)
    dev = timed_steady()

    # numpy oracle on a small prefix, extrapolated
    o_steps = max(steps // 50, 10)
    t0 = time.perf_counter()
    simulated_annealing(
        g, cfg, s0=s0[:1], proposals=proposals[:1, :o_steps],
        uniforms=uniforms[:1, :o_steps], max_steps=o_steps - 1, backend="cpu",
    )
    cpu = (time.perf_counter() - t0) * (steps / o_steps) * R

    rate = R * steps / dev
    report(
        "sa_mcmc_steps_per_sec_d3_rrg_n%d_r%d" % (n, R),
        rate,
        "mcmc-steps/s",
        vs_baseline=cpu / dev,
        # r01/r02 recorded this metric cold (jit compile inside the timed
        # region); flagged so cross-round diffs don't misread the change
        timing="steady_state",
    )

    # light-cone candidate evaluation (O(ball) per step vs O(n·d); chains
    # bit-identical — tests/test_sa.py::test_lightcone_bit_parity_with_full);
    # tables prebuilt so the steady-state metric measures per-step work
    from graphdyn.ops.lightcone import build_lightcone_tables

    tables = build_lightcone_tables(g, cfg.dynamics.p + cfg.dynamics.c - 1)
    lc = timed_steady(rollout_mode="lightcone", lc_tables=tables)
    report(
        "sa_mcmc_steps_per_sec_lightcone_n%d_r%d" % (n, R),
        R * steps / lc,
        "mcmc-steps/s",
        vs_baseline=cpu / lc,
        vs_full_rollout=dev / lc,
        timing="steady_state",
    )


def run_lightcone_scaling(n, R, steps):
    """Light-cone-only rungs at 10×/100× the BASELINE n: per-step work is
    O(ball), so the rate should hold roughly flat while the full rollout
    scales O(n) — the measured form of the scaling claim (see the known
    CPU-backend accept-scatter ceiling in graphdyn/ops/lightcone.py;
    whether XLA:TPU aliases the accept-scatter is exactly what the chip
    rungs answer — `SA_RRG.py:32-37` is the O(n·d) cost being killed).

    Tables are built ON DEVICE (`build_lightcone_tables_device`) and the
    spin state drawn on device: at n=1e6 the host path is ~100 s of Python
    BFS plus ~600 MB of table upload, which the tunneled TPU link cannot
    sustain. The metric name carries a ``_scaling`` tag so run()'s
    host-tables lightcone row at the same (n, R) never collides."""
    from graphdyn.ops.lightcone import build_lightcone_tables_device

    g, cfg, s0, proposals, uniforms = _setup(n, R, steps, device_s0=True)
    tables = build_lightcone_tables_device(
        g, cfg.dynamics.p + cfg.dynamics.c - 1
    )
    lc = _timed_steady(
        g, cfg, s0, proposals, uniforms, steps,
        rollout_mode="lightcone", lc_tables=tables,
    )
    report(
        "sa_mcmc_steps_per_sec_lightcone_scaling_n%d_r%d" % (n, R),
        R * steps / lc,
        "mcmc-steps/s",
        timing="steady_state",
        tables="device_built",
    )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    a = ap.parse_args()
    run(10_000 if a.full else 2000, 32, 2000 if a.full else 400)
    # the O(ball) scaling claim, measured: steps/s across decades of n
    # (flat = the accept-scatter aliases; falling = it copies — diagnose)
    run_lightcone_scaling(10_000 if a.full else 2000, 32,
                          1000 if a.full else 200)
    run_lightcone_scaling(100_000 if a.full else 20_000, 32,
                          1000 if a.full else 200)
    if a.full:
        run_lightcone_scaling(1_000_000, 32, 500)
