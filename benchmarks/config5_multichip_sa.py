#!/usr/bin/env python3
"""BASELINE config 5: d=5 RRG Ising SA, N=1e6, 1024 replicas × 16-point
temperature ladder, multi-chip psum.

On a multi-chip slice this runs the node+replica-sharded SA step
(`graphdyn.parallel.sharded.make_sharded_sa_step`) over the full mesh; on the
single tunneled chip (or a CPU mesh via
``XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu``)
it exercises the same sharded program at reduced shapes.
"""

import argparse
import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from benchmarks.common import report, timed
from graphdyn.graphs import random_regular_graph
from graphdyn.parallel.mesh import device_pool, make_mesh
from graphdyn.parallel.sharded import (
    make_sharded_sa_step,
    make_sharded_rollout,
    pad_nodes,
    place_sharded,
)


def run(n, R, n_temps):
    n_dev = len(jax.devices())
    node_shards = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
    rep_shards = max(n_dev // node_shards, 1)
    mesh = make_mesh(
        (rep_shards, node_shards), ("replica", "node"),
        devices=device_pool(rep_shards * node_shards),
    )
    g = random_regular_graph(n, 5, seed=0)
    nbr_pad, n_pad = pad_nodes(g, node_shards)
    Rtot = R * n_temps
    Rtot -= Rtot % max(rep_shards, 1)

    rng = np.random.default_rng(0)
    s = (2 * rng.integers(0, 2, size=(Rtot, n_pad)) - 1).astype(np.int8)
    nbr_d = place_sharded(mesh, jnp.asarray(nbr_pad), P("node", None))
    s_d = place_sharded(mesh, jnp.asarray(s), P("replica", "node"))

    rollout = make_sharded_rollout(mesh, n_real=g.n, steps=1)
    s_end = rollout(nbr_d, s_d)
    sum_end = jnp.asarray(
        np.asarray(s_end)[:, : g.n].astype(np.int64).sum(axis=1), jnp.int32
    )
    # temperature ladder: a0/b0 vary per replica block (BASELINE config 5);
    # tile the ladder across however many replicas survived the shard trim
    ladder = np.linspace(0.005, 0.03, n_temps)
    a0 = np.resize(np.repeat(ladder, max(Rtot // n_temps, 1)), Rtot)
    step = make_sharded_sa_step(mesh, rollout_steps=1, n_real=g.n)
    keys = jax.vmap(jax.random.PRNGKey)(np.arange(Rtot, dtype=np.uint32))
    args = (
        nbr_d, s_d,
        place_sharded(mesh, sum_end, P("replica")),
        place_sharded(mesh, jnp.asarray(a0 * g.n, jnp.float32), P("replica")),
        place_sharded(mesh, jnp.full((Rtot,), 0.01 * g.n, jnp.float32), P("replica")),
        place_sharded(mesh, keys, P("replica")),
        place_sharded(mesh, jnp.zeros((Rtot,), jnp.int32), P("replica")),
        jnp.float32(1.0005), jnp.float32(1.0005),
        jnp.float32(4.5 * g.n), jnp.float32(5.0 * g.n),
    )
    _, dt = timed(lambda *a: step(*a), *args)
    report(
        "multichip_sa_step_replica_rollouts_per_sec_d5_n%d" % n,
        Rtot / dt,
        "replica-steps/s",
        mesh=f"{rep_shards}x{node_shards}",
    )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    a = ap.parse_args()
    if a.full:
        run(1_000_000, 1024, 16)
    else:
        run(50_000, 16, 4)
