#!/usr/bin/env python3
"""BASELINE config 5: d=5 RRG Ising SA, N=1e6, 1024 replicas × 16-point
temperature ladder, multi-chip psum.

Two measurements over the replica×node mesh:

1. ``run_step`` — throughput of one full sharded SA step (proposal,
   candidate rollout with the tiled int8 all_gather, Metropolis, anneal,
   pmean'd consensus), the raw config-5 hot path.
2. ``run_solver`` — the END-TO-END sharded solver
   (:func:`graphdyn.parallel.sa_sharded.sa_sharded`): the consensus-stop
   ``lax.while_loop`` with per-replica freezing, annealing caps, and the
   timeout sentinel (`SA_RRG.py:72-85` semantics), reporting
   steps-to-consensus per replica and sustained step rate under a bounded
   ``max_steps``.

On a multi-chip slice this spans the full mesh; on the single tunneled chip
(or a CPU mesh via ``XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu``) the same sharded program runs at reduced shapes, with
device OOM probed by halving the replica count (capacity is measured, not
guessed).
"""

import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from benchmarks.common import report, timed
from graphdyn.config import DynamicsConfig, SAConfig
from graphdyn.graphs import random_regular_graph
from graphdyn.parallel.mesh import device_pool, make_mesh
from graphdyn.parallel.sharded import (
    make_sharded_sa_step,
    make_sharded_rollout,
    pad_nodes,
    place_sharded,
)
from graphdyn.parallel.sa_sharded import sa_sharded


def _mesh():
    n_dev = len(jax.devices())
    node_shards = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
    rep_shards = max(n_dev // node_shards, 1)
    mesh = make_mesh(
        (rep_shards, node_shards), ("replica", "node"),
        devices=device_pool(rep_shards * node_shards),
    )
    return mesh, rep_shards, node_shards


def run_step(n, R, n_temps):
    mesh, rep_shards, node_shards = _mesh()
    g = random_regular_graph(n, 5, seed=0)
    nbr_pad, n_pad = pad_nodes(g, node_shards)
    Rtot = R * n_temps
    Rtot -= Rtot % max(rep_shards, 1)

    def attempt(Rtot):
        from jax.sharding import NamedSharding

        from benchmarks.common import draw_pm1_int8

        nbr_d = place_sharded(mesh, jnp.asarray(nbr_pad), P("node", None))
        # spins drawn ON DEVICE, directly into the target sharding: the host
        # draw is 16 GB at the full config-5 shape — unholdable on the 1-core
        # host and unshippable over the tunneled TPU link (r04 session)
        s_d = draw_pm1_int8(
            0, (Rtot, n_pad),
            out_shardings=NamedSharding(mesh, P("replica", "node")),
        )

        rollout = make_sharded_rollout(mesh, n_real=g.n, steps=1)
        s_end = rollout(nbr_d, s_d)
        # device-side reduction (a host round-trip here pulls the full
        # [Rtot, n_pad] spin state back over the link)
        sum_end = jax.jit(
            lambda se: se[:, : g.n].astype(jnp.int32).sum(axis=1)
        )(s_end)
        # temperature ladder: a0 varies per replica block (BASELINE config 5)
        ladder = np.linspace(0.005, 0.03, n_temps)
        a0 = np.resize(np.repeat(ladder, max(Rtot // n_temps, 1)), Rtot)
        step = make_sharded_sa_step(mesh, rollout_steps=1, n_real=g.n)
        keys = jax.vmap(jax.random.PRNGKey)(np.arange(Rtot, dtype=np.uint32))
        args = (
            nbr_d, s_d,
            place_sharded(mesh, sum_end, P("replica")),
            place_sharded(mesh, jnp.asarray(a0 * g.n, jnp.float32), P("replica")),
            place_sharded(mesh, jnp.full((Rtot,), 0.01 * g.n, jnp.float32), P("replica")),
            place_sharded(mesh, keys, P("replica")),
            place_sharded(mesh, jnp.zeros((Rtot,), jnp.int32), P("replica")),
            jnp.float32(1.0005), jnp.float32(1.0005),
            jnp.float32(4.5 * g.n), jnp.float32(5.0 * g.n),
        )
        return timed(lambda *a: step(*a), *args)

    requested = Rtot
    from benchmarks.common import halve_on_oom

    (_, dt), Rtot = halve_on_oom(
        attempt, Rtot, floor=rep_shards, multiple=rep_shards
    )
    report(
        "multichip_sa_step_replica_rollouts_per_sec_d5_n%d" % n,
        Rtot / dt,
        "replica-steps/s",
        mesh=f"{rep_shards}x{node_shards}",
        replicas=Rtot,
        replicas_requested=requested,
    )


def run_solver(n, R, n_temps, max_steps, rollout_mode="full"):
    """End-to-end sharded solve: the consensus-stop loop with sentinels.

    ``rollout_mode='lightcone'`` runs the O(ball) candidate path on a
    replica-only mesh (each device holds whole replicas + trajectory
    caches) — the design the giant-graph × many-replica config-5 shape
    wants when per-device memory allows it."""
    if rollout_mode == "lightcone":
        n_dev = len(jax.devices())
        mesh, rep_shards, node_shards = (
            make_mesh((n_dev, 1), ("replica", "node"),
                      devices=device_pool(n_dev)),
            n_dev, 1,
        )
    else:
        mesh, rep_shards, node_shards = _mesh()
    g = random_regular_graph(n, 5, seed=0)
    Rtot = max(R * n_temps, rep_shards)
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))

    def attempt(Rt):
        ladder = np.resize(
            np.repeat(np.linspace(0.010, 0.020, n_temps), max(Rt // n_temps, 1)),
            Rt,
        )
        t0 = time.perf_counter()
        res = sa_sharded(
            g, cfg, mesh=mesh, n_replicas=Rt, seed=0,
            a0=ladder * g.n, max_steps=max_steps,
            rollout_mode=rollout_mode,
        )
        return res, time.perf_counter() - t0

    from benchmarks.common import halve_on_oom

    (res, dt), Rtot = halve_on_oom(
        attempt, Rtot, floor=rep_shards, multiple=rep_shards
    )
    converged = res.m_final == 1.0
    steps_total = int(res.num_steps.sum())
    suffix = "_lightcone" if rollout_mode == "lightcone" else ""
    report(
        "multichip_sa_solver%s_steps_per_sec_d5_n%d" % (suffix, n),
        steps_total / dt,
        "mcmc-steps/s",
        mesh=f"{rep_shards}x{node_shards}",
        replicas=Rtot,
        consensus_frac=float(converged.mean()),
        median_steps_to_consensus=(
            float(np.median(res.num_steps[converged])) if converged.any() else None
        ),
        max_steps=max_steps,
    )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    a = ap.parse_args()
    if a.full:
        run_step(1_000_000, 1024, 16)
        run_solver(20_000, 4, 4, max_steps=300_000)
        run_solver(100_000, 4, 4, max_steps=300_000, rollout_mode="lightcone")
    else:
        run_step(50_000, 16, 4)
        run_solver(1_000, 2, 2, max_steps=150_000)
        run_solver(1_000, 2, 2, max_steps=150_000, rollout_mode="lightcone")
