#!/usr/bin/env python3
"""BASELINE config 3: ER G(N, 6/N) majority-vote opinion dynamics, N=1e5,
512 replicas — the bit-packed replica kernel on a ragged degree sequence."""

import argparse
import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from benchmarks.common import report, timed
from graphdyn.graphs import erdos_renyi_graph
from graphdyn.ops.packed import packed_consensus_fraction, packed_rollout


def run(n, R, steps):
    g = erdos_renyi_graph(n, 6.0 / n, seed=0)
    W = -(-R // 32)  # ceil: pad replicas live in the top word's high bits
    rng = np.random.default_rng(0)
    sp = jnp.asarray(rng.integers(0, 2**32, size=(g.n, W), dtype=np.uint32))
    nbr = jnp.asarray(g.nbr)
    deg = jnp.asarray(g.deg)
    f = jax.jit(lambda sp: packed_rollout(nbr, deg, sp, steps))
    out, dt = timed(f, sp)
    report(
        "er_majority_spin_updates_per_sec_n%d_r%d" % (n, R),
        n * R * steps / dt,
        "spin-updates/s",
        consensus_fraction=packed_consensus_fraction(out, R),
    )


def run_consensus_sweep(n, R, m0_list, max_steps, chunk=10):
    """The config's PHYSICS, not just its GB/s: sweep the initial
    magnetization m(0) and record which initializations flow to opinion
    consensus — the phenomenon the BDCM entropy curves quantify
    (`ER_BDCM_entropy.ipynb:113-123`; thesis objective, SURVEY.md §0.3).

    Per m(0): near-consensus fraction (|m_final| ≥ 0.99 — robust to the
    O(1) frozen/blinking small components of sparse ER that block strict
    all-equal consensus at a rate set by component statistics, not by the
    dynamics), strict-consensus fraction, mean steps to near-consensus
    (resolution = ``chunk``), and mean |m_final|. One JSON line per m(0).
    The experiment driver lives in `graphdyn.models.consensus`; this config
    only reports its rows in the benchmark-JSON-line format."""
    from graphdyn.models.consensus import consensus_curve, er_consensus_ensemble

    g, n_iso, nbr_dev, deg_dev = er_consensus_ensemble(n)
    for pt in consensus_curve(g, R, m0_list, max_steps, chunk,
                              nbr_dev=nbr_dev, deg_dev=deg_dev):
        pt = dict(pt)
        frac = pt.pop("consensus_fraction")
        report(
            "er_majority_consensus_fraction_n%d_r%d_m0_%g"
            % (g.n, R, pt["m0"]),
            frac,
            "fraction",
            isolates_removed=n_iso,
            **pt,
        )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    a = ap.parse_args()
    run(100_000 if a.full else 20_000, 512, 20)
    run_consensus_sweep(
        100_000 if a.full else 20_000,
        512 if a.full else 128,
        (0.0, 0.02, 0.05, 0.1, 0.2, 0.3),
        max_steps=1000 if a.full else 300,
    )
