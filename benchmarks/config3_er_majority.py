#!/usr/bin/env python3
"""BASELINE config 3: ER G(N, 6/N) majority-vote opinion dynamics, N=1e5,
512 replicas — the bit-packed replica kernel on a ragged degree sequence."""

import argparse
import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from benchmarks.common import report, timed
from graphdyn.graphs import erdos_renyi_graph
from graphdyn.ops.packed import packed_consensus_fraction, packed_rollout


def run(n, R, steps):
    g = erdos_renyi_graph(n, 6.0 / n, seed=0)
    W = -(-R // 32)  # ceil: pad replicas live in the top word's high bits
    rng = np.random.default_rng(0)
    sp = jnp.asarray(rng.integers(0, 2**32, size=(g.n, W), dtype=np.uint32))
    nbr = jnp.asarray(g.nbr)
    deg = jnp.asarray(g.deg)
    f = jax.jit(lambda sp: packed_rollout(nbr, deg, sp, steps))
    out, dt = timed(f, sp)
    report(
        "er_majority_spin_updates_per_sec_n%d_r%d" % (n, R),
        n * R * steps / dt,
        "spin-updates/s",
        consensus_fraction=packed_consensus_fraction(out, R),
    )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    a = ap.parse_args()
    run(100_000 if a.full else 20_000, 512, 20)
