#!/usr/bin/env python3
"""BASELINE config 3: ER G(N, 6/N) majority-vote opinion dynamics, N=1e5,
512 replicas — the bit-packed replica kernel on a ragged degree sequence."""

import argparse
import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from benchmarks.common import report, timed
from graphdyn.graphs import erdos_renyi_graph
from graphdyn.ops.packed import packed_consensus_fraction, packed_rollout


def run(n, R, steps):
    g = erdos_renyi_graph(n, 6.0 / n, seed=0)
    W = -(-R // 32)  # ceil: pad replicas live in the top word's high bits
    rng = np.random.default_rng(0)
    sp = jnp.asarray(rng.integers(0, 2**32, size=(g.n, W), dtype=np.uint32))
    nbr = jnp.asarray(g.nbr)
    deg = jnp.asarray(g.deg)
    f = jax.jit(lambda sp: packed_rollout(nbr, deg, sp, steps))
    out, dt = timed(f, sp)
    report(
        "er_majority_spin_updates_per_sec_n%d_r%d" % (n, R),
        n * R * steps / dt,
        "spin-updates/s",
        consensus_fraction=packed_consensus_fraction(out, R),
    )


def consensus_point(g, R, m0, max_steps, chunk=10, seed=1000,
                    nbr_dev=None, deg_dev=None):
    """One m(0) point of the opinion-consensus curve on a prepared graph:
    biased device-resident init, chunked consensus scan, per-replica
    statistics reduced to a plain dict (shared by this config's sweep and
    ``scripts/physics_consensus.py``). Callers sweeping many m(0) points
    pass ``nbr_dev``/``deg_dev`` once — re-uploading the multi-MB neighbor
    table per point is tunnel traffic the link cannot sustain."""
    from graphdyn.ops.packed import draw_packed_biased, packed_consensus_scan

    W = -(-R // 32)
    sp = draw_packed_biased(seed, g.n, W, m0)
    nbr_dev = jnp.asarray(g.nbr) if nbr_dev is None else nbr_dev
    deg_dev = jnp.asarray(g.deg) if deg_dev is None else deg_dev
    out = packed_consensus_scan(
        nbr_dev, deg_dev, sp,
        R=W * 32, max_steps=max_steps, chunk=chunk,
    )
    near = np.asarray(out["near"])[:R]
    near_step = np.asarray(out["near_step"])[:R]
    m_final = np.asarray(out["m_final"])[:R]
    n_near = int(near.sum())
    return {
        "m0": float(m0),
        "consensus_fraction": n_near / R,
        "strict_fraction": float(np.asarray(out["strict"])[:R].mean()),
        "mean_steps_to_consensus": (
            float(near_step[near].mean()) if n_near else None
        ),
        "mean_abs_m_final": float(np.abs(m_final).mean()),
        "max_steps": int(max_steps),
        "step_resolution": int(chunk),
        "replicas": int(R),
    }


def consensus_ensemble(n):
    """The config-3 opinion-dynamics ensemble, defined ONCE for every
    consumer (this config's sweep and ``scripts/physics_consensus.py``):
    ER G(n, 6/n) seed 0 with isolates removed, mirroring the reference's
    analytic isolate treatment (`ER_BDCM_entropy.ipynb:283-291`). Returns
    (graph, n_isolates, nbr_device, deg_device) — tables uploaded once."""
    from graphdyn.graphs import remove_isolates

    g, n_iso = remove_isolates(erdos_renyi_graph(n, 6.0 / n, seed=0))
    return g, n_iso, jnp.asarray(g.nbr), jnp.asarray(g.deg)


def consensus_curve(g, R, m0_list, max_steps, chunk=10, nbr_dev=None,
                    deg_dev=None, progress=None):
    """The m(0)→consensus curve as a list of row dicts (one per m(0),
    seed-offset 1000+k). ``progress`` is an optional per-row callback."""
    rows = []
    for k, m0 in enumerate(m0_list):
        pt = consensus_point(g, R, m0, max_steps, chunk, seed=1000 + k,
                             nbr_dev=nbr_dev, deg_dev=deg_dev)
        rows.append(pt)
        if progress is not None:
            progress(pt)
    return rows


def run_consensus_sweep(n, R, m0_list, max_steps, chunk=10):
    """The config's PHYSICS, not just its GB/s: sweep the initial
    magnetization m(0) and record which initializations flow to opinion
    consensus — the phenomenon the BDCM entropy curves quantify
    (`ER_BDCM_entropy.ipynb:113-123`; thesis objective, SURVEY.md §0.3).

    Per m(0): near-consensus fraction (|m_final| ≥ 0.99 — robust to the
    O(1) frozen/blinking small components of sparse ER that block strict
    all-equal consensus at a rate set by component statistics, not by the
    dynamics), strict-consensus fraction, mean steps to near-consensus
    (resolution = ``chunk``), and mean |m_final|. One JSON line per m(0)."""
    g, n_iso, nbr_dev, deg_dev = consensus_ensemble(n)
    for pt in consensus_curve(g, R, m0_list, max_steps, chunk,
                              nbr_dev=nbr_dev, deg_dev=deg_dev):
        pt = dict(pt)
        frac = pt.pop("consensus_fraction")
        report(
            "er_majority_consensus_fraction_n%d_r%d_m0_%g"
            % (g.n, R, pt["m0"]),
            frac,
            "fraction",
            isolates_removed=n_iso,
            **pt,
        )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    a = ap.parse_args()
    run(100_000 if a.full else 20_000, 512, 20)
    run_consensus_sweep(
        100_000 if a.full else 20_000,
        512 if a.full else 128,
        (0.0, 0.02, 0.05, 0.1, 0.2, 0.3),
        max_steps=1000 if a.full else 300,
    )
