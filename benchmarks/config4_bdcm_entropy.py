#!/usr/bin/env python3
"""BASELINE config 4: BDCM entropy sweep, 64 graph instances × 32 λ points.

Measures full λ-ladder wall time (graph build + factor tables + warm-started
fixed points + observables) per instance.
"""

import argparse
import sys
import time

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import numpy as np

from benchmarks.common import report
from graphdyn.config import EntropyConfig
from graphdyn.graphs import erdos_renyi_graph
from graphdyn.models.entropy import entropy_sweep


def run(n, n_graphs, n_lambda):
    cfg = EntropyConfig(max_sweeps=400)
    lambdas = np.linspace(0.0, 3.1, n_lambda)
    # per-graph (host-loop) path: a capped sample — it exists for graphs
    # with isolates; the vmapped congruent-ensemble below is the TPU-first
    # path and carries the full BASELINE shape
    n_pg = min(n_graphs, 8)
    t0 = time.perf_counter()
    done = 0
    for k in range(n_pg):
        g = erdos_renyi_graph(n, 1.5 / (n - 1), seed=k)
        # class_bucket pads degree-class sizes to a shared grid so the
        # instances reuse a handful of compiled programs instead of
        # recompiling per graph (compile time dominates otherwise)
        res = entropy_sweep(g, cfg, seed=k, lambdas=lambdas, class_bucket=64)
        done += res.lambdas.size
    dt = time.perf_counter() - t0
    report(
        "bdcm_entropy_lambda_points_per_sec_n%d" % n,
        done / dt,
        "lambda-points/s",
        graphs=n_pg,
    )

    # union-ensemble path: the TRUE config-4 workload — the heterogeneous ER
    # ensemble (different degree signatures, isolates) × the λ ladder as ONE
    # device program via the disjoint union (single big edge axis)
    from graphdyn.models.entropy import entropy_ensemble_union

    er_graphs = [
        erdos_renyi_graph(n, 1.5 / (n - 1), seed=k) for k in range(n_graphs)
    ]
    t0 = time.perf_counter()
    res = entropy_ensemble_union(er_graphs, cfg, seed=0, lambdas=lambdas)
    dt = time.perf_counter() - t0
    report(
        "bdcm_entropy_union_ensemble_graph_lambda_points_per_sec_n%d" % n,
        res.lambdas.size * n_graphs / dt,
        "graph-lambda-points/s",
        graphs=n_graphs,
        union=True,
    )

    # mesh-sharded union path: same workload with every fixed point
    # edge-sharded over the devices (make_sharded_fixed_point); on one
    # device this is skipped — the unsharded number above is the metric
    import jax

    n_dev = len(jax.devices())
    if n_dev > 1:
        from graphdyn.parallel.mesh import make_mesh

        emesh = make_mesh((n_dev,), ("edge",))
        t0 = time.perf_counter()
        res = entropy_ensemble_union(
            er_graphs, cfg, seed=0, lambdas=lambdas, mesh=emesh
        )
        dt = time.perf_counter() - t0
        report(
            "bdcm_entropy_union_mesh_graph_lambda_points_per_sec_n%d" % n,
            res.lambdas.size * n_graphs / dt,
            "graph-lambda-points/s",
            graphs=n_graphs,
            union=True,
            mesh="%dx1" % n_dev,
        )

    # vmapped congruent-ensemble path (RRG members share one signature)
    from graphdyn.graphs import random_regular_graph
    from graphdyn.models.entropy import entropy_ensemble

    graphs = [random_regular_graph(n, 3, seed=k) for k in range(n_graphs)]
    t0 = time.perf_counter()
    res = entropy_ensemble(graphs, cfg, seed=0, lambdas=lambdas)
    dt = time.perf_counter() - t0
    report(
        "bdcm_entropy_ensemble_graph_lambda_points_per_sec_n%d" % n,
        res.lambdas.size * n_graphs / dt,
        "graph-lambda-points/s",
        graphs=n_graphs,
        vmapped=True,
    )

    # graph-axis-sharded congruent ensemble: instances are independent, so
    # the vmapped program partitions embarrassingly over the mesh (shard
    # count capped so the graph count divides it)
    g_shards = n_dev
    while n_graphs % g_shards:
        g_shards //= 2
    if n_dev > 1 and g_shards > 1:
        from graphdyn.parallel.mesh import make_mesh

        gmesh = make_mesh((g_shards,), ("graph",), devices=jax.devices()[:g_shards])
        t0 = time.perf_counter()
        res = entropy_ensemble(graphs, cfg, seed=0, lambdas=lambdas, mesh=gmesh)
        dt = time.perf_counter() - t0
        report(
            "bdcm_entropy_ensemble_mesh_graph_lambda_points_per_sec_n%d" % n,
            res.lambdas.size * n_graphs / dt,
            "graph-lambda-points/s",
            graphs=n_graphs,
            vmapped=True,
            mesh="%dx1" % g_shards,
        )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    a = ap.parse_args()
    if a.full:
        run(1000, 64, 32)
    else:
        run(300, 4, 8)
