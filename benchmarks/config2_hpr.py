#!/usr/bin/env python3
"""BASELINE config 2: HPr relaxation, d=3 RRG, N=1e5, 256 replicas.

Measures reinforced-BP message-update throughput (directed-edge messages ×
trajectory combos per second) of the jitted HPr iteration body, the
reference's hot path (`HPR_pytorch_RRG.py:183-218`).
"""

import argparse
import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import jax.numpy as jnp

from benchmarks.common import report, timed
from graphdyn.graphs import random_regular_graph
from graphdyn.ops.bdcm import BDCMData, make_marginals, make_sweep


def torch_sweep_seconds(data, lmbd=25.0, damp=0.4, iters=2):
    """One reference-style HPr sweep in single-threaded torch on CPU — the
    north-star divisor (BASELINE.md: '>=50x the PyTorch HPR baseline').

    This re-implements the sweep MATH of `HPR_pytorch_RRG.py:183-218`
    (neighbor ρ-lattice DP, factor contraction, λ-tilt, normalization,
    damping) as an efficient vectorized torch program on the same tables —
    deliberately far more favorable to the baseline than the reference's
    actual per-combo `order_gpu` string-parsing host loop, so the reported
    speedup is an *underestimate*. Returns seconds per sweep."""
    import time as _time

    import numpy as np
    import torch

    torch.set_num_threads(1)
    from graphdyn.attractors import trajectories01, x0_pm

    K, T = data.K, data.T
    X01 = trajectories01(T)
    tilt = torch.as_tensor(np.exp(-lmbd * x0_pm(T)), dtype=torch.float32)
    chi = torch.as_tensor(np.asarray(data.init_messages(0)))
    classes = [
        (cls.d, torch.as_tensor(np.asarray(cls.idx, np.int64)),
         torch.as_tensor(np.asarray(cls.in_edges, np.int64)),
         torch.as_tensor(np.asarray(cls.A, np.float32)))
        for cls in data.edge_classes
    ]

    def sweep_once(chi):
        out = chi.clone()
        for d, idx, in_edges, A in classes:
            chi_in = chi[in_edges]                        # [Ed, d, K, K]
            Ed = chi_in.shape[0]
            LL = torch.zeros((Ed, K) + (d + 1,) * T)
            LL[(slice(None), slice(None)) + (0,) * T] = 1.0
            lat_axes = tuple(range(2, 2 + T))
            for D in range(d):
                acc = torch.zeros_like(LL)
                for k_idx in range(K):
                    shift = tuple(int(b) for b in X01[k_idx])
                    sh = torch.roll(LL, shift, lat_axes) if any(shift) else LL
                    w = chi_in[:, D, k_idx, :]
                    acc = acc + sh * w.reshape(w.shape + (1,) * T)
                LL = acc
            LL = LL.reshape(Ed, K, -1)
            chi2 = torch.einsum("xym,exm->exy", A, LL) * tilt[None, :, None]
            z = chi2.sum(dim=(1, 2), keepdim=True).clamp_min(
                torch.finfo(chi2.dtype).tiny
            )
            chi2 = chi2 / z
            out[idx] = damp * chi2 + (1.0 - damp) * chi[idx]
        return out

    sweep_once(chi)                                       # warm caches
    t0 = _time.perf_counter()
    for _ in range(iters):
        chi = sweep_once(chi)
    return (_time.perf_counter() - t0) / iters


def run(n, sweeps):
    g = random_regular_graph(n, 3, seed=0)
    data = BDCMData(g, p=1, c=1)
    sweep = make_sweep(data, damp=0.4, mask_invalid_src=False, with_bias=True)
    marginals = make_marginals(data)
    chi = data.init_messages_device(0)      # no host chi upload (tunneled link)
    bias = jnp.ones((data.num_directed, data.K), jnp.float32)

    @jax.jit
    def body(chi):
        chi = sweep(chi, jnp.float32(25.0), bias)
        return chi, marginals(chi)

    (_, _), dt = timed(lambda c: body(c), chi, iters=sweeps)
    torch_dt = torch_sweep_seconds(data)
    msg_rate = data.num_directed * data.K * data.K / dt
    report(
        "hpr_message_updates_per_sec_d3_rrg_n%d" % n,
        msg_rate,
        "message-combos/s",
        sweeps_per_sec=1.0 / dt,
        # the BASELINE.md north star (">=50x the PyTorch HPR baseline"),
        # measured against a vectorized single-thread torch-CPU sweep on
        # this host — flattering to the baseline vs the reference's actual
        # per-combo host loop, so this ratio is an underestimate
        vs_baseline=torch_dt / dt,
        baseline_kind="torch_cpu_single_thread_vectorized_sweep",
        torch_sweep_s=torch_dt,
    )


def run_replicas(n, R, sweeps):
    """Replica-batched iteration throughput (BASELINE config 2's `256
    replicas` axis): R chains' sweep+marginals as one device program.

    Replicas batch as a DISJOINT-UNION graph in the REPLICA-MAJOR edge
    layout (`graphdyn.models.hpr.union_setup`): the edge axis stays the one
    big lane dimension (memory linear in R — a leading-axis ``vmap`` pads
    the replica dim to 128 lanes, measured R-independent 2.3 GB temps at
    n=1e5, OOM), and replica r owns contiguous rows [r·2E, (r+1)·2E). On a
    multi-device slice the program runs under ``shard_map`` with each device
    sweeping its own R/n_dev-replica block with purely LOCAL gathers — the
    canonical-union layout instead made GSPMD all-gather chi every sweep
    (the round-3 17× per-combo collapse). Capacity is still *measured*:
    halve R on device OOM until the program fits.
    """
    from benchmarks.common import halve_on_oom
    from graphdyn.config import HPRConfig
    from graphdyn.models.hpr import union_setup

    n_dev = len(jax.devices())
    g = random_regular_graph(n, 3, seed=0)
    cfg = HPRConfig()

    def attempt(R):
        # shard only when each device gets a whole replica block; small or
        # non-divisible R (halve_on_oom can floor at 1) runs single-device
        use_mesh = n_dev > 1 and R >= n_dev and R % n_dev == 0
        R_local = R // n_dev if use_mesh else R
        # single-device: union tables + chi built ON DEVICE — the host
        # builders' ~4 GB upload is what the tunneled TPU link cannot
        # sustain (r04 session); the mesh path keeps the host build (its
        # chi must be host-sharded across devices anyway)
        setup = union_setup(g, cfg, R_local, device=not use_mesh)
        bias_l = jnp.ones((setup.data.num_directed, setup.data.K), jnp.float32)

        def body_local(chi):
            chi = setup.sweep(chi, jnp.float32(25.0), bias_l)
            return chi, setup.marginals(chi)

        if use_mesh:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from graphdyn.parallel.mesh import make_mesh, shard_map

            mesh = make_mesh((n_dev,), ("replica",))
            rep = P("replica")
            body = jax.jit(shard_map(
                body_local, mesh=mesh, in_specs=(rep,), out_specs=(rep, rep),
                check_vma=False,
            ))
            # chi drawn ON DEVICE straight into the replica sharding — a
            # host draw at reference scale is ~10 GB over the link
            from graphdyn.ops.bdcm import draw_chi_device

            chi = draw_chi_device(
                jax.random.key(0), 2 * g.num_edges * R, setup.data.K,
                jnp.float32, out_shardings=NamedSharding(mesh, rep),
            )
        else:
            body = jax.jit(body_local)
            chi = setup.data.init_messages_device(0)

        class _Data:
            num_directed = 2 * g.num_edges * R
            K = setup.data.K

        (_, _), dt = timed(lambda c: body(c), chi, iters=sweeps)
        return _Data, dt

    requested = R
    (data, dt), R = halve_on_oom(attempt, R, floor=1, multiple=max(n_dev, 1))
    report(
        "hpr_replica_message_updates_per_sec_d3_rrg_n%d_r%d" % (n, R),
        data.num_directed * data.K * data.K / dt,
        "message-combos/s",
        sweeps_per_sec=1.0 / dt,
        replicas=R,
        replicas_requested=requested,
    )


def run_t3(n, sweeps):
    """T=3 regime (p=2, c=1, d=4 ⇒ K=8, 125-slot ρ-lattice): the trajectory
    horizon the fused Pallas DP kernel accelerates (PALLAS_TPU.md §2
    measured 4.1× at (d−1, T) = (3, 3) on chip), exercised END-TO-END as an
    HPr iteration (sweep + marginals) with the kernel on vs off. Off-TPU
    both rows take the XLA path (auto disables Pallas), so the A/B is
    meaningful on chip; the config still runs everywhere as a T-scaling
    throughput number (`HPR_pytorch_RRG.py:241-242` — the 2^{2T} combo
    axis)."""
    g = random_regular_graph(n, 4, seed=0)
    data = BDCMData(g, p=2, c=1)
    marginals = make_marginals(data)
    chi = data.init_messages_device(0)      # no host chi upload (tunneled link)
    bias = jnp.ones((data.num_directed, data.K), jnp.float32)
    for use_pallas, tag in (("auto", "pallas_auto"), (False, "xla")):
        sweep = make_sweep(
            data, damp=0.4, mask_invalid_src=False, with_bias=True,
            use_pallas=use_pallas,
        )

        @jax.jit
        def body(chi, sweep=sweep, marginals=marginals, bias=bias):
            chi = sweep(chi, jnp.float32(25.0), bias)
            return chi, marginals(chi)

        # warmup=2: the first executed T=3 program additionally pays
        # process-level allocator/page warming for its ~160 MB lattice
        # temps, which one warmup call does not fully absorb — measured as
        # a spurious 2x first-row penalty on CPU (identical programs time
        # identically when re-measured back-to-back)
        (_, _), dt = timed(lambda c: body(c), chi, iters=sweeps, warmup=2)
        report(
            "hpr_t3_message_updates_per_sec_d4_rrg_n%d_%s" % (n, tag),
            data.num_directed * data.K * data.K / dt,
            "message-combos/s",
            sweeps_per_sec=1.0 / dt,
            T=3,
            backend=jax.default_backend(),
        )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    a = ap.parse_args()
    if a.full:
        run(100_000, 20)
        run_replicas(100_000, 256, 5)
        run_t3(100_000, 10)
    else:
        run(10_000, 20)
        run_replicas(10_000, 8, 5)
        run_t3(10_000, 5)
