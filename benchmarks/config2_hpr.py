#!/usr/bin/env python3
"""BASELINE config 2: HPr relaxation, d=3 RRG, N=1e5, 256 replicas.

Measures reinforced-BP message-update throughput (directed-edge messages ×
trajectory combos per second) of the jitted HPr iteration body, the
reference's hot path (`HPR_pytorch_RRG.py:183-218`).
"""

import argparse
import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import jax.numpy as jnp

from benchmarks.common import report, timed
from graphdyn.graphs import random_regular_graph
from graphdyn.ops.bdcm import BDCMData, make_marginals, make_sweep


def run(n, sweeps):
    g = random_regular_graph(n, 3, seed=0)
    data = BDCMData(g, p=1, c=1)
    sweep = make_sweep(data, damp=0.4, mask_invalid_src=False, with_bias=True)
    marginals = make_marginals(data)
    chi = data.init_messages(0)
    bias = jnp.ones((data.num_directed, data.K), jnp.float32)

    @jax.jit
    def body(chi):
        chi = sweep(chi, jnp.float32(25.0), bias)
        return chi, marginals(chi)

    (_, _), dt = timed(lambda c: body(c), chi, iters=sweeps)
    msg_rate = data.num_directed * data.K * data.K / dt
    report(
        "hpr_message_updates_per_sec_d3_rrg_n%d" % n,
        msg_rate,
        "message-combos/s",
        sweeps_per_sec=1.0 / dt,
    )


def run_replicas(n, R, sweeps):
    """Replica-batched iteration throughput (BASELINE config 2's `256
    replicas` axis): R chains' sweep+marginals as one device program.

    Replicas batch as a DISJOINT-UNION graph (R structural copies side by
    side, `graphdyn.graphs.replicate_disjoint`): the edge axis stays the one
    big lane dimension, so memory scales linearly in R — a ``vmap`` over a
    leading replica axis instead makes XLA pad the replica dim to 128 lanes
    (R-independent 2.3 GB temps at n=1e5, measured OOM). On a multi-device
    slice the union's edge/node-blocked state shards over a 1-D mesh (chains
    are disjoint, so shard-crossing gathers are rare). Capacity is still
    *measured*: halve R on device OOM until the program fits.
    """
    from benchmarks.common import halve_on_oom

    n_dev = len(jax.devices())
    g = random_regular_graph(n, 3, seed=0)

    def attempt(R):
        from graphdyn.graphs import replicate_disjoint

        gu = replicate_disjoint(g, R)
        data = BDCMData(gu, p=1, c=1)
        sweep = make_sweep(data, damp=0.4, mask_invalid_src=False, with_bias=True)
        marginals = make_marginals(data)
        chi = data.init_messages(0)
        bias = jnp.ones((data.num_directed, data.K), jnp.float32)
        if n_dev > 1:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from graphdyn.parallel.mesh import make_mesh

            mesh = make_mesh((n_dev,), ("replica",))
            chi = jax.device_put(chi, NamedSharding(mesh, P("replica")))
            bias = jax.device_put(bias, NamedSharding(mesh, P("replica")))

        @jax.jit
        def body(chi):
            chi = sweep(chi, jnp.float32(25.0), bias)
            return chi, marginals(chi)

        (_, _), dt = timed(lambda c: body(c), chi, iters=sweeps)
        return data, dt

    requested = R
    (data, dt), R = halve_on_oom(attempt, R, floor=1, multiple=max(n_dev, 1))
    report(
        "hpr_replica_message_updates_per_sec_d3_rrg_n%d_r%d" % (n, R),
        data.num_directed * data.K * data.K / dt,
        "message-combos/s",
        sweeps_per_sec=1.0 / dt,
        replicas=R,
        replicas_requested=requested,
    )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    a = ap.parse_args()
    if a.full:
        run(100_000, 20)
        run_replicas(100_000, 256, 5)
    else:
        run(10_000, 20)
        run_replicas(10_000, 8, 5)
