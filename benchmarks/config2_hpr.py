#!/usr/bin/env python3
"""BASELINE config 2: HPr relaxation, d=3 RRG, N=1e5, 256 replicas.

Measures reinforced-BP message-update throughput (directed-edge messages ×
trajectory combos per second) of the jitted HPr iteration body, the
reference's hot path (`HPR_pytorch_RRG.py:183-218`).
"""

import argparse
import sys

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import jax
import jax.numpy as jnp

from benchmarks.common import report, timed
from graphdyn.graphs import random_regular_graph
from graphdyn.ops.bdcm import BDCMData, make_marginals, make_sweep


def run(n, sweeps):
    g = random_regular_graph(n, 3, seed=0)
    data = BDCMData(g, p=1, c=1)
    sweep = make_sweep(data, damp=0.4, mask_invalid_src=False, with_bias=True)
    marginals = make_marginals(data)
    chi = data.init_messages(0)
    bias = jnp.ones((data.num_directed, data.K), jnp.float32)

    @jax.jit
    def body(chi):
        chi = sweep(chi, jnp.float32(25.0), bias)
        return chi, marginals(chi)

    (_, _), dt = timed(lambda c: body(c), chi, iters=sweeps)
    msg_rate = data.num_directed * data.K * data.K / dt
    report(
        "hpr_message_updates_per_sec_d3_rrg_n%d" % n,
        msg_rate,
        "message-combos/s",
        sweeps_per_sec=1.0 / dt,
    )


def run_replicas(n, R, sweeps):
    """Replica-batched iteration throughput (BASELINE config 2's `256
    replicas` axis): R chains' sweep+marginals as one device program.

    The vmapped body's DP intermediates scale with R·E; the replica count is
    capped to what a chip's HBM can hold (~32 at n=1e5 per ~16 GB) times the
    device count, with the replica axis sharded over the mesh beyond one
    device — the same layout ``hpr_solve_batch(mesh=...)`` uses.
    """
    n_dev = len(jax.devices())
    # HBM bound scales with 1/n: ~32 replicas fit per ~16 GB chip at n=1e5
    per_dev = max(1, int(32 * 1e5 / n))
    R = min(R, per_dev * max(n_dev, 1))
    g = random_regular_graph(n, 3, seed=0)
    data = BDCMData(g, p=1, c=1)
    sweep = make_sweep(data, damp=0.4, mask_invalid_src=False, with_bias=True)
    marginals = make_marginals(data)
    vsweep = jax.vmap(sweep, in_axes=(0, None, 0))
    vmarg = jax.vmap(marginals)
    chi = jnp.stack([data.init_messages(k) for k in range(R)])
    bias = jnp.ones((R, data.num_directed, data.K), jnp.float32)
    if n_dev > 1 and R % n_dev == 0:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from graphdyn.parallel.mesh import make_mesh

        mesh = make_mesh((n_dev,), ("replica",))
        shard = NamedSharding(mesh, P("replica"))
        chi = jax.device_put(chi, shard)
        bias = jax.device_put(bias, shard)

    @jax.jit
    def body(chi):
        chi = vsweep(chi, jnp.float32(25.0), bias)
        return chi, vmarg(chi)

    (_, _), dt = timed(lambda c: body(c), chi, iters=sweeps)
    report(
        "hpr_replica_message_updates_per_sec_d3_rrg_n%d_r%d" % (n, R),
        R * data.num_directed * data.K * data.K / dt,
        "message-combos/s",
        sweeps_per_sec=1.0 / dt,
        replicas=R,
    )


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--full", action="store_true")
    a = ap.parse_args()
    if a.full:
        run(100_000, 20)
        run_replicas(100_000, 256, 5)
    else:
        run(10_000, 20)
        run_replicas(10_000, 8, 5)
