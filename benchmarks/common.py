"""Shared benchmark harness: timing + one-JSON-line reporting.

Importing this module makes the repo root importable, so the config scripts
run from any cwd (``python /path/to/benchmarks/configN_*.py``).
"""

from __future__ import annotations

import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

# GRAPHDYN_FORCE_PLATFORM: forces the jax platform before first use (plugins
# can pin jax_platforms at startup, where JAX_PLATFORMS alone cannot win) —
# one shared implementation with the CLI, see graphdyn.utils.platform
from graphdyn.utils.platform import apply_force_platform

apply_force_platform()


def probe_relay(budget_s: float, probe_timeout: float = 75.0) -> bool:
    """Probe the TPU relay in short, disposable subprocess attempts until a
    chip backend answers or ``budget_s`` is spent; True when the chip is up.

    A wedged relay hangs jax client init forever *in-process* (there is no
    retry after that), so probing happens in subprocesses and the caller
    only touches jax once a probe succeeds. The relay recovers in
    minutes-long windows, so short repeated probes convert outages a single
    long wait would lose. A probe that *completes* with a CPU backend is
    deterministic evidence no chip plugin exists in this environment —
    terminal, no retry (only hangs/timeouts justify retrying).

    Callers that get False should force CPU (``GRAPHDYN_FORCE_PLATFORM=cpu``)
    and label their output a fallback, not a chip number.
    """
    import subprocess

    # the probe child states plugin PRESENCE before it touches jax device
    # init: a fast-failing attempt with a PJRT chip plugin installed is a
    # transient relay/plugin error (the relay recovers in windows — keep
    # probing within the budget), while the same fast failure with NO
    # plugin registered is deterministic 'no chip here' (terminal). The
    # r05 misclassification: a relay whose plugin raised quickly was read
    # as a broken install after three strikes and the window was lost.
    code = (
        "import os\n"
        "import importlib.metadata as md\n"
        "try:\n"
        "    names = sorted({ep.name for ep in"
        " md.entry_points(group='jax_plugins')})\n"
        "except Exception:\n"
        "    names = []\n"
        "if os.environ.get('PJRT_NAMES_AND_LIBRARY_PATHS'):\n"
        "    names.append('pjrt-env')\n"
        "print('PROBE_PLUGINS', ','.join(names) or '-', flush=True)\n"
        "import jax, jax.numpy as jnp\n"
        "jax.devices()\n"
        "(jnp.ones((128, 128)) @ jnp.ones((128, 128))).block_until_ready()\n"
        "print('PROBE_OK', jax.default_backend())\n"
    )
    deadline = time.monotonic() + budget_s
    attempt = fast_fails = 0
    while True:
        left = deadline - time.monotonic()
        if left <= 0:
            return False
        attempt += 1
        t0 = time.monotonic()
        try:
            p = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True, text=True,
                timeout=min(probe_timeout, max(left, 15.0)),
            )
            if (p.returncode == 0
                    and any(f"PROBE_OK {b}" in p.stdout
                            for b in ("tpu", "axon"))):
                print(f"[probe] attempt {attempt}: chip up", file=sys.stderr,
                      flush=True)
                return True
            if p.returncode == 0 and "PROBE_OK" in p.stdout:
                print(f"[probe] attempt {attempt}: completed on a non-chip "
                      "backend — no chip in this environment, not retrying",
                      file=sys.stderr, flush=True)
                return False
            # completed-but-failed (rc != 0): transient relay error, or
            # deterministic breakage? The plugin marker decides. Plugin
            # PRESENT -> init failed, which is exactly what a bouncing
            # relay looks like: keep probing within the budget. Plugin
            # ABSENT (or the child died before the marker) -> three
            # consecutive fast failures = deterministic, stop burning the
            # budget; a wedge manifests as a hang/timeout, never as a
            # quick clean exit.
            marker = [ln for ln in p.stdout.splitlines()
                      if ln.startswith("PROBE_PLUGINS ")]
            plugin_present = bool(marker) and marker[0].split(None, 1)[1] != "-"
            if time.monotonic() - t0 < 10.0 and not plugin_present:
                fast_fails += 1
                if fast_fails >= 3:
                    print(f"[probe] attempt {attempt}: third consecutive "
                          "fast failure with no PJRT plugin registered — "
                          "deterministic, not retrying; "
                          f"last stderr: {p.stderr.strip()[-200:]}",
                          file=sys.stderr, flush=True)
                    return False
            else:
                if plugin_present and p.returncode != 0:
                    print(f"[probe] attempt {attempt}: plugin present "
                          f"({marker[0].split(None, 1)[1]}) but init "
                          "failed — transient, keep probing",
                          file=sys.stderr, flush=True)
                fast_fails = 0
        except subprocess.TimeoutExpired:
            fast_fails = 0
        left = deadline - time.monotonic()
        print(f"[probe] attempt {attempt}: down ({max(left, 0):.0f}s budget "
              "left)", file=sys.stderr, flush=True)
        # near the deadline, shorten the pause instead of sleeping the rest
        # of the budget away — the final window still gets a probe attempt
        # (the subprocess timeout floor of 15 s may overshoot slightly)
        time.sleep(20.0 if left > 25.0 else min(2.0, max(left, 0.0)))


def probe_or_cpu_fallback(budget_s: float | None = None) -> str | None:
    """Entry-point guard for capture scripts: when no platform is forced,
    probe the relay and force CPU if it never answers, returning a
    fallback-label note (None when the chip is up or a force was already
    set). Must run BEFORE first in-process jax backend use. Pair with
    :func:`init_watchdog` around the first jax call — the relay can wedge
    in the window between a successful probe and the in-process init."""
    if os.environ.get("BENCH_CPU_REEXEC"):
        # we are the post-wedge re-exec of init_watchdog: the CPU force was
        # set by the watchdog, not the caller — label the capture
        return ("relay wedged between probe and init; "
                "this capture is a CPU fallback, NOT chip numbers")
    if os.environ.get("GRAPHDYN_FORCE_PLATFORM"):
        return None
    budget = (float(os.environ.get("BENCH_INIT_BUDGET_S", "600"))
              if budget_s is None else budget_s)
    t0 = time.monotonic()
    if probe_relay(budget):
        return None
    elapsed = time.monotonic() - t0
    os.environ["GRAPHDYN_FORCE_PLATFORM"] = "cpu"
    from graphdyn.utils.platform import apply_force_platform

    apply_force_platform()
    # elapsed, not budget: a deterministic give-up (no chip plugin, fast
    # failures) happens in seconds — the label must not claim minutes of
    # relay unreachability that never elapsed
    return (f"no chip backend after {elapsed:.0f}s of probing "
            f"(budget {budget:.0f}s); this capture is a CPU fallback, "
            "NOT chip numbers")


def init_watchdog(timeout_s: float = 300.0, allow_cpu_fallback: bool = True,
                  fail_row: dict | None = None):
    """Backstop for a relay that wedges *between* a successful probe and the
    in-process jax init (which then hangs unrecoverably): after ``timeout_s``
    without the returned event being set, re-exec the process with the
    platform forced to CPU so the capture still lands as a real,
    fallback-labeled artifact (``probe_or_cpu_fallback`` detects the re-exec
    and returns the label). With ``allow_cpu_fallback=False`` (the caller
    explicitly forced a platform — chip-or-hang semantics), or when the
    CPU re-exec itself hangs (cannot happen: CPU init never touches the
    tunnel), print ``fail_row`` as JSON if given and exit 2.

    Call ``.set()`` on the returned event as soon as the first jax device
    call completes."""
    import threading

    done = threading.Event()

    def watch():
        if not done.wait(timeout_s):
            if allow_cpu_fallback and not os.environ.get("BENCH_CPU_REEXEC"):
                print(f"[init-watchdog] device init hung {timeout_s:.0f}s "
                      "after a successful probe; re-exec with CPU fallback",
                      file=sys.stderr, flush=True)
                os.environ["BENCH_CPU_REEXEC"] = "1"
                os.environ["GRAPHDYN_FORCE_PLATFORM"] = "cpu"
                os.execv(sys.executable, [sys.executable] + sys.argv)
            if fail_row is not None:
                print(json.dumps(fail_row), flush=True)
            else:
                print("[init-watchdog] device init hung "
                      f"{timeout_s:.0f}s; exiting", file=sys.stderr,
                      flush=True)
            os._exit(2)

    threading.Thread(target=watch, daemon=True).start()
    return done


def guarded_capture_init(fail_row: dict | None = None,
                         timeout_s: float = 300.0) -> str | None:
    """The one chip-or-hang entry preamble for every capture script
    (bench.py, scripts/physics_consensus*.py): probe-or-fallback, arm the
    init watchdog, touch the first device, disarm. Returns the fallback
    label note (None when on chip / explicitly forced). One implementation
    so the force/re-exec interaction cannot drift between scripts.

    ``fail_row`` (optional JSON row printed if even the watchdog path
    hangs) gets an ``error`` text filled in based on whether the caller
    explicitly forced a platform (chip-or-hang) or not."""
    explicit = (bool(os.environ.get("GRAPHDYN_FORCE_PLATFORM"))
                and not os.environ.get("BENCH_CPU_REEXEC"))
    note = probe_or_cpu_fallback()
    if fail_row is not None and "error" not in fail_row:
        fail_row = dict(fail_row)
        fail_row["error"] = (
            "device init hung under an explicitly forced platform "
            "(chip-or-hang)" if explicit
            else "device init hung even under CPU force")
    done = init_watchdog(timeout_s, allow_cpu_fallback=not explicit,
                         fail_row=fail_row)
    import jax

    jax.devices()
    done.set()
    return note


def _sync(out):
    """Wait for ``out`` for real: ``block_until_ready`` plus a one-element
    device-to-host read. On the tunneled TPU platform, ``block_until_ready``
    has been observed returning early after any >64 MB execution (timings
    collapse to dispatch overhead — see PALLAS_TPU.md); a D2H read cannot
    complete before the producing execution has, and the device executes
    in-order, so this fences every dispatched iteration."""
    import jax
    import numpy as np

    jax.block_until_ready(out)
    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "ravel") and getattr(leaf, "size", 0) > 0:
            np.asarray(leaf.ravel()[0])
            break


def draw_u32(seed: int, shape, out_shardings=None):
    """Uniform uint32 words drawn ON DEVICE (optionally directly into a
    sharding). Benchmark states must never be host-drawn then uploaded: a
    512 MB+ host→device payload over the tunneled TPU link is the r04
    session's measured failure mode."""
    import jax
    import jax.numpy as jnp

    f = lambda: jax.random.bits(jax.random.key(seed), shape, jnp.uint32)  # noqa: E731
    out = jax.jit(f, out_shardings=out_shardings)()
    _sync(out)
    return out


def draw_pm1_int8(seed: int, shape, out_shardings=None):
    """±1 int8 spins drawn ON DEVICE (see :func:`draw_u32` for why)."""
    import jax
    import jax.numpy as jnp

    def f():
        b = jax.random.bernoulli(jax.random.key(seed), 0.5, shape)
        return 2 * b.astype(jnp.int8) - 1

    out = jax.jit(f, out_shardings=out_shardings)()
    _sync(out)
    return out


def timed(fn, *args, warmup: int = 1, iters: int = 3):
    """Run ``fn`` ``warmup`` times uncounted, then ``iters`` timed; returns
    (last_result, seconds_per_iter)."""
    out = None
    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return out, (time.perf_counter() - t0) / iters


def report(metric: str, value: float, unit: str, vs_baseline: float | None = None, **extra):
    line = {"metric": metric, "value": value, "unit": unit}
    if vs_baseline is not None:
        line["vs_baseline"] = vs_baseline
    line.update(extra)
    print(json.dumps(line))


def is_oom(e: Exception) -> bool:
    """True for device out-of-memory errors (XLA RESOURCE_EXHAUSTED)."""
    s = str(e)
    return "RESOURCE_EXHAUSTED" in s or "Out of memory" in s or "OOM" in s


def halve_on_oom(attempt, R: int, floor: int = 1, multiple: int = 1):
    """Call ``attempt(R)``, halving R on device OOM until it fits.

    ``floor`` is the smallest admissible R; ``multiple`` keeps every tried R
    divisible (e.g. by the replica-shard count, so sharding constraints stay
    satisfiable). Returns ``(result, achieved_R)``; re-raises non-OOM errors.
    """
    def snap(r):
        return max(floor, r - r % multiple if multiple > 1 else r)

    R = snap(R)
    while True:
        try:
            return attempt(R), R
        except Exception as e:  # noqa: BLE001 — halve only on device OOM
            if not is_oom(e) or R <= floor:
                raise
            R = snap(R // 2)
