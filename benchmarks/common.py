"""Shared benchmark harness: timing + one-JSON-line reporting.

Importing this module makes the repo root importable, so the config scripts
run from any cwd (``python /path/to/benchmarks/configN_*.py``).
"""

from __future__ import annotations

import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def timed(fn, *args, warmup: int = 1, iters: int = 3):
    """Run ``fn`` ``warmup`` times uncounted, then ``iters`` timed; returns
    (last_result, seconds_per_iter)."""
    import jax

    out = None
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return out, (time.perf_counter() - t0) / iters


def report(metric: str, value: float, unit: str, vs_baseline: float | None = None, **extra):
    line = {"metric": metric, "value": value, "unit": unit}
    if vs_baseline is not None:
        line["vs_baseline"] = vs_baseline
    line.update(extra)
    print(json.dumps(line))
