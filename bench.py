#!/usr/bin/env python3
"""Headline benchmark: spin-updates/sec/chip on d=3 RRG (BASELINE.json).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``vs_baseline`` is the speedup over the reference-style torch-CPU dynamics
kernel (`HPR_pytorch_RRG.py:169-171` semantics) measured on this host.

Usage: python bench.py [--smoke]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def tpu_rate(nbr, n, R, steps, iters=3):
    import jax
    import jax.numpy as jnp

    from graphdyn.ops.dynamics import batched_rollout_impl, rule_coefficients

    R_coef, C_coef = rule_coefficients("majority", "stay")
    nbr_dev = jnp.asarray(nbr)

    @jax.jit
    def roll(s):
        # the shipped hot kernel — bench measures the real code path
        return batched_rollout_impl(nbr_dev, s, steps, R_coef, C_coef)

    rng = np.random.default_rng(0)
    s = jnp.asarray((2 * rng.integers(0, 2, size=(R, n)) - 1).astype(np.int8))
    jax.block_until_ready(roll(s))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        s = roll(s)
    jax.block_until_ready(s)
    dt = time.perf_counter() - t0
    return n * R * steps * iters / dt


def torch_cpu_rate(nbr, n, steps=3):
    import torch

    nbr_t = torch.as_tensor(nbr.astype(np.int64))
    rng = np.random.default_rng(0)
    s = torch.as_tensor((2 * rng.integers(0, 2, size=n) - 1).astype(np.int64))
    # warm
    sums = torch.sum(s[nbr_t], dim=1)
    _ = (1 - torch.abs(torch.sign(sums))) * s + torch.sign(sums)
    t0 = time.perf_counter()
    for _ in range(steps):
        sums = torch.sum(s[nbr_t], dim=1)
        s = (1 - torch.abs(torch.sign(sums))) * s + torch.sign(sums)
    dt = time.perf_counter() - t0
    return n * steps / dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="small shapes, fast")
    ap.add_argument("--replicas", type=int, default=None)
    ap.add_argument("--steps", type=int, default=None)
    args = ap.parse_args()

    from graphdyn.graphs import random_regular_graph

    if args.smoke:
        n, R, steps = 100_000, 8, 5
    else:
        n, R, steps = 1_000_000, 64, 20
    R = args.replicas or R
    steps = args.steps or steps

    g = random_regular_graph(n, 3, seed=0)
    nbr = np.asarray(g.nbr)

    value = tpu_rate(nbr, n, R, steps)
    base = torch_cpu_rate(nbr, n)
    print(
        json.dumps(
            {
                "metric": "spin_updates_per_sec_per_chip_d3_rrg_n%d" % n,
                "value": value,
                "unit": "spin-updates/s",
                "vs_baseline": value / base,
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
