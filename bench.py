#!/usr/bin/env python3
"""Headline benchmark: spin-updates/sec/chip on d=3 RRG (BASELINE.json).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

The headline value is the bit-packed replica kernel
(`graphdyn.ops.packed`: 32 replicas per uint32 word, carry-save-adder
counting) at N=1e6 × 4096 replicas — the framework's ensemble-dynamics hot
path. ``vs_baseline`` is the speedup over the reference-style torch-CPU
dynamics kernel (`HPR_pytorch_RRG.py:169-171` semantics) measured on this
host. The int8 batched-rollout rate is reported alongside, plus the
``ensemble_rate`` row: end-to-end DRIVER throughput (grouped pipeline vs
legacy serial loop on the same workload, ``ensemble_speedup`` = their
wall-clock ratio). Rows skipped on the current backend (wide-replica,
Pallas) emit ``null`` + ``<row>_skipped_reason``, never 0.0.

Usage: python bench.py [--smoke]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np


def _sync(x):
    """Reliable device fence (see benchmarks/common._sync and PALLAS_TPU.md:
    bare ``block_until_ready`` returns early after large executions on the
    tunneled platform)."""
    from benchmarks.common import _sync as fence

    fence(x)


def _mark(msg):
    """Stage marker on stderr: locates where a wedged/slow run is spending
    time (host build vs tunnel transfer vs compile vs compute) without
    touching the single-JSON-line stdout contract."""
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def trend_gate(row):
    """The cross-round rate gate (graphdyn.obs.trend): diff this round's
    rows against the latest comparable committed ``BENCH_r*.json``. The
    verdict rides IN the row (``obs_trend_status`` + findings) so benchcheck
    can assert the gate ran — or was explicitly skipped — and fail on
    unblessed drift. Never kills bench: a broken gate is a null status plus
    a reason, not a lost round."""
    import os

    if os.environ.get("GRAPHDYN_SKIP_TRENDGATE") == "1":
        return {"obs_trend_status": "skipped",
                "obs_trend_skipped_reason": "GRAPHDYN_SKIP_TRENDGATE=1"}
    try:
        from graphdyn.obs.trend import check_trend

        findings, status = check_trend(row, diag=_mark)
        out = {"obs_trend_status": status}
        if findings:
            out["obs_trend_findings"] = [
                {"row": f.row, "code": f.code, "message": f.message}
                for f in findings
            ]
        return out
    except Exception as e:  # noqa: BLE001 — telemetry must not kill bench
        _mark(f"trend gate failed: {str(e)[:150]}")
        return {"obs_trend_status": None,
                "obs_trend_skipped_reason":
                    f"trend gate failed: {str(e)[:150]}"}


def peak_hbm_row():
    """The device-memory column (graphdyn.obs.memband): the process-peak
    HBM bytes after the headline kernels ran — the occupancy number the
    TPU Ising literature reports next to the step rate. Null + reason on
    backends without usable memory_stats (CPU), never a silent absence or
    a fake 0."""
    try:
        from graphdyn.obs.memband import peak_hbm_bytes

        peak, reason = peak_hbm_bytes()
    except Exception as e:  # noqa: BLE001 — telemetry must not kill bench
        peak, reason = None, f"memory stats failed: {str(e)[:120]}"
    if peak is None:
        return {"peak_hbm_bytes": None,
                "peak_hbm_bytes_skipped_reason": reason}
    return {"peak_hbm_bytes": peak}


def packed_rate(g, R, steps, iters=3, kernel="xla"):
    import jax
    import jax.numpy as jnp

    from graphdyn.ops.packed import packed_rollout

    n = g.n
    W = R // 32
    nbr = jnp.asarray(g.nbr)
    from benchmarks.common import draw_u32

    _mark(f"packed_rate n={n} R={R} kernel={kernel}: on-device spin-word "
          f"draw ({n * W * 4 / 1e6:.0f} MB state)")
    sp = draw_u32(0, (n, W))
    _mark("packed_rate: state resident; compile+warmup")
    if kernel == "pallas":
        from graphdyn.ops.pallas_packed import pallas_packed_rollout

        deg_h = np.asarray(g.deg)
        # the rollout is jitted internally (host-side support gate outside)
        f = lambda sp: pallas_packed_rollout(nbr, deg_h, sp, steps)  # noqa: E731
        _sync(f(sp))
    else:
        deg = jnp.asarray(g.deg)
        # donate the chained state: the timing loop feeds each call's output
        # into the next, so without donation the 512 MB state at the full
        # shape is double-buffered for the whole loop
        f = jax.jit(lambda sp: packed_rollout(nbr, deg, sp, steps),
                    donate_argnums=0)
        sp = f(sp)                      # warmup consumes the drawn state
        _sync(sp)
    _mark("packed_rate: warm; timing")
    from graphdyn import obs

    # the one timing idiom (obs.timed): always measures; when bench runs
    # under a recorder the span + rate gauge land in the event ledger too
    with obs.timed("bench.packed_rate", n=n, R=R, kernel=kernel) as sw:
        for _ in range(iters):
            sp = f(sp)                  # chained: each call consumes the last
        _sync(sp)
    rate = n * R * steps * iters / sw.wall_s
    obs.gauge("ops.packed.rate", rate, n=n, R=R, kernel=kernel)
    return rate


def int8_rate(g, R, steps, iters=3):
    import jax
    import jax.numpy as jnp

    from graphdyn.ops.dynamics import batched_rollout_impl, rule_coefficients

    from benchmarks.common import draw_pm1_int8

    R_coef, C_coef = rule_coefficients("majority", "stay")
    nbr = jnp.asarray(g.nbr)
    s = draw_pm1_int8(0, (R, g.n))
    # chained timing loop — donate so the [R, n] state updates in place
    f = jax.jit(lambda s: batched_rollout_impl(nbr, s, steps, R_coef, C_coef),
                donate_argnums=0)
    s = f(s)
    _sync(s)
    from graphdyn import obs

    with obs.timed("bench.int8_rate", n=g.n, R=R) as sw:
        for _ in range(iters):
            s = f(s)
        _sync(s)
    rate = g.n * R * steps * iters / sw.wall_s
    obs.gauge("ops.int8.rate", rate, n=g.n, R=R)
    return rate


def ensemble_rate(smoke: bool):
    """End-to-end DRIVER throughput (spin-updates/s through ``sa_ensemble``,
    host graph sampling included) — the number the pipeline changes, where
    the kernel rows above cannot see driver overhead. Runs the same
    workload twice per path (grouped pipeline vs legacy serial loop; the
    first run pays the XLA compile, the second is measured) and reports the
    warm rates plus their wall-clock ratio. Results are element-wise
    identical between the paths (tested), so this is a pure execution-
    schedule A/B."""
    from graphdyn.config import DynamicsConfig, SAConfig
    from graphdyn.models.sa import sa_ensemble

    if smoke:
        n, n_stat, max_steps, group = 512, 16, 300, 16
    else:
        n, n_stat, max_steps, group = 8192, 32, 500, 32
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    kw = dict(n_stat=n_stat, seed=0, max_steps=max_steps)

    from graphdyn import obs

    walls = {}
    updates = {}
    for label, gs in (("serial", 0), ("grouped", group)):
        _mark(f"ensemble_rate {label}: warmup (compile)")
        sa_ensemble(n, 3, cfg, group_size=gs, **kw)
        _mark(f"ensemble_rate {label}: timing")
        with obs.timed("bench.ensemble_rate", path=label) as sw:
            res = sa_ensemble(n, 3, cfg, group_size=gs, **kw)
        walls[label] = sw.wall_s
        updates[label] = n * int(np.sum(res.num_steps))
    return {
        "ensemble_rate": updates["grouped"] / walls["grouped"],
        "ensemble_rate_serial": updates["serial"] / walls["serial"],
        "ensemble_speedup": walls["serial"] / walls["grouped"],
        "ensemble_workload": {"n": n, "d": 3, "n_stat": n_stat,
                              "max_steps": max_steps, "group_size": group},
    }


def entropy_cell_rate(smoke: bool):
    """Grouped-vs-serial A/B on the entropy grid driver (cell-parallel BDCM
    λ-ladders, ``graphdyn.pipeline.entropy_group``): the same deg × rep
    workload through the serial cell loop (``group_size=0``) and the
    stacked cell-group program, warm rates in cell-λ points/s plus the
    wall-clock ratio. Results are element-wise identical between the paths
    (tested), so this is a pure execution-schedule A/B.

    Cell batching trades per-cell cache residency for lane parallelism —
    the win is accelerator lanes, and on a small-core CPU the batched
    working set falls out of L2 and measures SLOWER than serial. When the
    measured ratio does not clear 1.2×, the row reports ``null`` + a
    reason carrying the measured ratio (never a 0.0 that could read as a
    collapse), keeping the emitted speedup an honest chip-class signal."""
    import jax
    from graphdyn.config import DynamicsConfig, EntropyConfig
    from graphdyn.models.entropy import entropy_grid

    if smoke:
        n, degs, reps, group, bucket = 32, [1.0, 1.3], 3, 6, 16
        cfg = EntropyConfig(
            dynamics=DynamicsConfig(p=1, c=1), lmbd_max=0.3, lmbd_step=0.1,
            num_rep=reps, max_sweeps=200, eps=1e-4,
        )
    else:
        n, degs, reps, group, bucket = 256, [1.0, 1.5, 2.0], 8, 24, 64
        cfg = EntropyConfig(
            dynamics=DynamicsConfig(p=1, c=1), lmbd_max=0.5, lmbd_step=0.1,
            num_rep=reps, max_sweeps=400, eps=1e-5,
        )
    # the XLA legs measure the execution-schedule A/B (grouped vs serial);
    # on chip backends a third leg A/Bs the grouped-Pallas kernel against
    # grouped-XLA on the same workload (kernel tag in the row). Interpret
    # mode is not a rate, so the Pallas leg is chip-only — skipped with an
    # explicit reason, never a 0.0
    on_chip = jax.default_backend() in ("tpu", "axon")
    legs = [("serial", 0, "xla"), ("grouped", group, "xla")]
    if on_chip:
        legs.append(("grouped_pallas", group, "pallas"))
    from graphdyn import obs

    walls, points = {}, {}
    for label, gs, kern in legs:
        kw = dict(seed=0, group_size=gs, class_bucket=bucket, kernel=kern)
        _mark(f"entropy_cell_rate {label} [kernel={kern}]: warmup (compile)")
        entropy_grid(n, np.asarray(degs), cfg, **kw)
        _mark(f"entropy_cell_rate {label} [kernel={kern}]: timing")
        with obs.timed("bench.entropy_cell_rate", path=label,
                       kernel=kern) as sw:
            r = entropy_grid(n, np.asarray(degs), cfg, **kw)
        walls[label] = sw.wall_s
        points[label] = int(np.sum(r.n_lambda))
    speedup = walls["serial"] / walls["grouped"]
    workload = {"n": n, "deg": degs, "num_rep": reps, "group_size": group,
                "lambda_points": points["grouped"],
                # which sweep core each leg ran (the Pallas A/B tag)
                "kernel": {label: kern for label, _, kern in legs}}
    if on_chip:
        pallas_row = {
            "entropy_cell_rate_pallas":
                points["grouped_pallas"] / walls["grouped_pallas"],
            "entropy_cell_pallas_speedup":
                walls["grouped"] / walls["grouped_pallas"],
        }
    else:
        pallas_row = {
            "entropy_cell_rate_pallas": None,
            "entropy_cell_rate_pallas_skipped_reason": (
                "grouped-Pallas A/B is chip-only (backend=%s): interpret "
                "mode is not a rate" % jax.default_backend()
            ),
        }
    if speedup < 1.2:
        return {
            "entropy_cell_rate": None,
            "entropy_cell_rate_skipped_reason": (
                f"grouped cell ladder measured {speedup:.2f}x vs serial on "
                f"this host (backend={jax.default_backend()}): cell "
                "batching trades per-cell cache residency for lane "
                "parallelism — an accelerator-lane win, not a small-core-"
                f"CPU one; serial rate "
                f"{points['serial'] / walls['serial']:.1f} cell-lambda/s"
            ),
            "entropy_cell_speedup_measured": speedup,
            **pallas_row,
            "entropy_cell_workload": workload,
        }
    return {
        "entropy_cell_rate": points["grouped"] / walls["grouped"],
        "entropy_cell_rate_serial": points["serial"] / walls["serial"],
        "entropy_cell_speedup": speedup,
        **pallas_row,
        "entropy_cell_workload": workload,
    }


def ckpt_save_overhead(smoke: bool):
    """The durable-store tax on the checkpoint hot path: p50/p99 save
    latency of `graphdyn.resilience.store.DurableCheckpoint` (checksum
    manifest + versioned promote + retention + journal) vs the raw
    `Checkpoint.save` it wraps, on the entropy-chunk snapshot shape (the
    repo's largest per-interval payload: warm-start chi + the grid row
    arrays). Null + reason on failure, never silent — the row keeps the
    durability tax honest round-over-round the way the rate rows keep
    throughput honest."""
    import os
    import shutil
    import tempfile

    from graphdyn import obs
    from graphdyn.resilience.store import DurableCheckpoint
    from graphdyn.utils.io import Checkpoint

    if smoke:
        n, reps = 2_000, 15
    else:
        n, reps = 20_000, 40
    E = int(n * 1.5 / 2)                # ER deg=1.5 edge count
    K, L = 2, 121                       # p=c=1 alphabet; λ ladder length
    rng = np.random.default_rng(0)
    arrays = {
        "chi": rng.random((2 * E, K, K)),
        "grid_ent": rng.random((3, 8, L)),
        "grid_m_init": rng.random((3, 8, L)),
        "grid_ent1": rng.random((3, 8, L)),
        "grid_sweeps": rng.integers(0, 1300, (3, 8, L)),
        "lambdas": np.arange(L) * 0.1,
    }
    meta = {"grid_id": "bench", "next_cell": [0, 0]}
    root = tempfile.mkdtemp(prefix="graphdyn_bench_ckpt_")
    try:
        # mirror/keep pinned: the A/B must measure the store itself, not
        # whatever GRAPHDYN_CKPT_MIRROR/_KEEP happen to be in the caller's
        # environment (a configured mirror would both skew the durable leg
        # with replication work and litter the user's real mirror directory
        # with throwaway bench files)
        stores = (
            ("raw", Checkpoint(os.path.join(root, "raw", "ck"))),
            ("durable", DurableCheckpoint(os.path.join(root, "dur", "ck"),
                                          mirror=None, keep=2)),
        )
        times: dict = {label: [] for label, _ in stores}
        for _, ck in stores:
            ck.save(arrays, meta)       # warmup: makedirs, first manifest
        # INTERLEAVED A/B: back-to-back same-path batches read page-cache /
        # frequency drift as a store difference (measured 2x swings);
        # alternating saves give both stores the same ambient conditions
        for _ in range(reps):
            for label, ck in stores:
                with obs.timed("bench.ckpt_save", path=label) as sw:
                    ck.save(arrays, meta)
                times[label].append(sw.wall_s)
        out = {}
        for label, _ in stores:
            out[label + "_p50_s"] = float(np.percentile(times[label], 50))
            out[label + "_p99_s"] = float(np.percentile(times[label], 99))
        snapshot_bytes = os.path.getsize(os.path.join(root, "raw", "ck.npz"))
        return {"ckpt_save_overhead": {
            **out,
            "overhead_p50_x": out["durable_p50_s"] / out["raw_p50_s"],
            "overhead_p99_x": out["durable_p99_s"] / out["raw_p99_s"],
            "snapshot_bytes": int(snapshot_bytes),
            "saves": reps,
        }}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def heartbeat_overhead(smoke: bool):
    """The liveness tax: interleaved A/B of the same entropy smoke workload
    with the supervision watchdog ON (thread + beat-age polls; a generous
    stall timeout so it never fires) vs OFF. Heartbeats themselves are
    unconditional at every chunk/rep/λ boundary, so the row proves the
    WHOLE liveness stack — beats + watchdog — is measurably near-free;
    `beats_per_run` confirms the workload actually heartbeats. Null +
    reason on failure, never silent (benchcheck asserts the contract)."""
    import contextlib

    from graphdyn import obs
    from graphdyn.config import DynamicsConfig, EntropyConfig
    from graphdyn.models.entropy import entropy_grid
    from graphdyn.resilience import supervisor as _sup

    cfg = EntropyConfig(
        dynamics=DynamicsConfig(p=1, c=1),
        lmbd_max=0.2, lmbd_step=0.1, eps=1e-5, damp=0.1,
        max_sweeps=120, num_rep=1,
    )
    reps = 3 if smoke else 6

    def run_once() -> int:
        n0 = _sup.last_beat()[0]
        entropy_grid(48, np.asarray([1.5]), cfg, seed=0)
        return _sup.last_beat()[0] - n0

    beats = run_once()                  # warmup: pays the compile
    legs = (
        ("off", contextlib.nullcontext),
        # stall timeout far above the workload's runtime: the watchdog
        # must RUN (poll loop reading beat ages) without ever escalating
        ("on", lambda: _sup.supervision(stall_timeout_s=60.0)),
    )
    times: dict = {label: [] for label, _ in legs}
    # INTERLEAVED legs for the same reason as ckpt_save_overhead: back-to-
    # back batches read ambient drift as a watchdog difference
    for _ in range(reps):
        for label, cm in legs:
            with cm():
                with obs.timed("bench.heartbeat", leg=label) as sw:
                    run_once()
            times[label].append(sw.wall_s)
    out = {}
    for label, _ in legs:
        out[label + "_p50_s"] = float(np.percentile(times[label], 50))
    return {"heartbeat_overhead": {
        **out,
        "overhead_p50_x": out["on_p50_s"] / out["off_p50_s"],
        "beats_per_run": int(beats),
        "runs": reps,
    }}


def serve_bucket_hit_rate(smoke: bool):
    """The serve bucketing payoff: a multi-tenant queue where tenants
    repeat graphs (the serving steady state) drained through the real
    `graphdyn.serve.Worker`, reporting the BucketCache hit rate. Two
    graph identities, many jobs each — the expected rate is (jobs-2)/jobs
    and anything near zero means the cache key broke and every job is
    paying the table build again. Null + reason on failure, never
    silent."""
    import shutil
    import tempfile

    from graphdyn.serve.spool import Spool
    from graphdyn.serve.worker import Worker

    per_graph = 3 if smoke else 6
    root = tempfile.mkdtemp(prefix="graphdyn_bench_serve_")
    try:
        spool = Spool(root)
        base = {"n": 24, "d": 3, "max_sweeps": 16, "chunk_sweeps": 8}
        for i in range(per_graph):
            # two tenants, two graph identities, interleaved — the
            # multi-tenant repeat-graph steady state
            spool.submit({**base, "graph_seed": 0, "seed": i}, "alice")
            spool.submit({**base, "graph_seed": 1, "seed": i}, "bob")
        worker = Worker(spool)
        jobs = worker.run_until_drained()
        stats = worker.cache.stats()
        return {"serve_bucket_hit_rate": {
            "hit_rate": stats["hit_rate"],
            "hits": stats["hits"],
            "misses": stats["misses"],
            "resident_graphs": stats["resident_graphs"],
            "jobs": jobs,
        }}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def serve_job_latency(smoke: bool):
    """End-to-end serve latency per job (claim → admit → dispatch → run →
    result on disk), p50/p99, INTERLEAVED warm/cold legs: warm jobs
    repeat a cached graph identity, cold jobs bring a fresh graph each
    time (the table build is their tax; the compiled program is shared —
    same shape class — which is the bucketing claim this row keeps
    honest). Alternating submissions give both legs the same ambient
    conditions, same as ckpt_save_overhead. Null + reason on failure,
    never silent."""
    import shutil
    import tempfile

    from graphdyn import obs
    from graphdyn.serve.spool import PENDING, Spool
    from graphdyn.serve.worker import Worker

    reps = 4 if smoke else 10
    root = tempfile.mkdtemp(prefix="graphdyn_bench_serve_lat_")
    try:
        spool = Spool(root)
        base = {"n": 24, "d": 3, "max_sweeps": 16, "chunk_sweeps": 8}
        # warmup job first (FIFO spool): pays the compile + the warm
        # graph's table build outside the timed window
        leg_of = {spool.submit(dict(base), "warm"): None}
        for i in range(reps):
            leg_of[spool.submit({**base, "graph_seed": 100 + i},
                                "cold")] = "cold"
            leg_of[spool.submit({**base, "seed": i + 1}, "warm")] = "warm"
        worker = Worker(spool)
        times: dict = {"warm": [], "cold": []}
        while True:
            nxt = [r for r in spool.jobs() if r["state"] == PENDING]
            if not nxt:
                break
            leg = leg_of[nxt[0]["id"]]
            with obs.timed("bench.serve_job", leg=leg or "warmup") as sw:
                if not worker.step():
                    break
            if leg:
                times[leg].append(sw.wall_s)
        out = {}
        for leg in ("warm", "cold"):
            out[leg + "_p50_s"] = float(np.percentile(times[leg], 50))
            out[leg + "_p99_s"] = float(np.percentile(times[leg], 99))
        return {"serve_job_latency": {
            **out,
            "cold_over_warm_p50_x": out["cold_p50_s"] / out["warm_p50_s"],
            "jobs": 2 * reps,
        }}
    finally:
        shutil.rmtree(root, ignore_errors=True)


def halo_weak_scaling(smoke: bool, *, n_per=None, R=None, steps=None,
                      iters=None):
    """Weak scaling of the halo-exchange node sharding
    (``graphdyn.parallel.halo``): FIXED nodes per shard, P ∈ {1, 2, 4, 8}
    shards over however many devices this process sees (chips on a pod, a
    forced host-device CPU mesh under the test harness), efficiency =
    rate(P) / (P · rate(1)). The P=1 leg runs the unsharded packed program
    — exactly the ``partition=`` path's identity — so the efficiency
    column prices the exchange + shard bookkeeping and nothing else.
    ``halo_bytes_per_step`` reports the measured partition's exchange
    traffic (4·W·Σ ghosts — the edge cut in bytes). Fewer than 2 devices
    emits null + reason, never 0.0."""
    import jax
    import jax.numpy as jnp

    from graphdyn import obs
    from graphdyn.graphs import partition_graph, random_regular_graph
    from graphdyn.ops.packed import packed_rollout

    # ONE device pool for every leg: the default platform when it can host
    # a 2-shard mesh, else the (possibly simulated) CPU host platform for
    # ALL of P=1..8 — mixing a chip-rate P=1 leg with CPU-fallback P>=2
    # legs would emit a "measured" efficiency comparing different hardware
    pool = jax.devices()
    if len(pool) < 2:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= 2:
            pool = cpu
    if len(pool) < 2:
        reason = (
            f"halo weak scaling needs >= 2 devices on one platform (have "
            f"{len(pool)}); on CPU force a simulated host mesh: "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
        return {
            "halo_weak_efficiency": None,
            "halo_weak_efficiency_skipped_reason": reason,
            "halo_bytes_per_step": None,
            "halo_bytes_per_step_skipped_reason": reason,
        }
    avail = len(pool)
    from graphdyn.parallel.halo import HaloProgram
    from graphdyn.parallel.mesh import make_mesh

    defaults = (2048, 256, 10, 2) if smoke else (65536, 1024, 20, 3)
    # keyword overrides exist for the in-suite contract test (tiny shapes)
    n_per = n_per if n_per is not None else defaults[0]
    R = R if R is not None else defaults[1]
    steps = steps if steps is not None else defaults[2]
    iters = iters if iters is not None else defaults[3]
    W = R // 32
    from benchmarks.common import draw_u32

    rates: dict[str, float] = {}
    bytes_per_step = None
    for Pn in (1, 2, 4, 8):
        if Pn > avail:
            break
        g = random_regular_graph(Pn * n_per, 3, seed=0)
        sp = draw_u32(0, (g.n, W))
        if Pn == 1:
            # the P=1 leg runs the unsharded program on the SAME pool's
            # first device (operand placement pins the platform)
            nbr = jax.device_put(jnp.asarray(g.nbr), pool[0])
            deg = jax.device_put(jnp.asarray(g.deg), pool[0])
            f = jax.jit(lambda x: packed_rollout(nbr, deg, x, steps),
                        donate_argnums=0)
            st = f(jax.device_put(jnp.asarray(sp), pool[0]))
            _sync(st)
            with obs.timed("bench.halo_weak", P=Pn) as sw:
                for _ in range(iters):
                    st = f(st)
                _sync(st)
        else:
            part = partition_graph(g, Pn, seed=0)
            mesh = make_mesh((Pn,), ("node",), devices=pool[:Pn])
            prog = HaloProgram(g, part, steps=steps, mesh=mesh)
            st = prog.advance(prog.place(np.asarray(sp)))
            _sync(st)
            with obs.timed("bench.halo_weak", P=Pn) as sw:
                for _ in range(iters):
                    st = prog.advance(st)
                _sync(st)
            bytes_per_step = int(prog.tables.halo_bytes_per_step(W))
        rates[str(Pn)] = g.n * R * steps * iters / sw.wall_s
        obs.gauge("ops.halo.rate", rates[str(Pn)], P=Pn, n=g.n, R=R)
        _mark(f"halo weak scaling P={Pn}: n={g.n} rate {rates[str(Pn)]:.3e}")
    p_max = max(int(k) for k in rates)
    return {
        "halo_weak_efficiency": rates[str(p_max)] / (p_max * rates["1"]),
        "halo_rate_by_shards": rates,
        "halo_bytes_per_step": bytes_per_step,
        "halo_workload": {"n_per_shard": n_per, "d": 3, "R": R,
                          "steps": steps, "iters": iters, "P_max": p_max,
                          "platform": pool[0].platform},
    }


def powerlaw_rate_row(smoke: bool, *, n=None, R=None, steps=None,
                      iters=None):
    """Degree-bucketed power-law fast path vs the padded equal-edge RRG
    baseline (ROADMAP item 3): a seeded configuration-model power-law
    graph — the hub-heavy regime where the padded ``nbr[n, dmax]`` table
    explodes — runs through ``graphdyn.ops.bucketed.bucketed_rollout``;
    the control is a random-regular graph with (approximately) the same
    edge count through the padded ``packed_rollout``. Both legs count the
    same ``n·R·steps`` spin updates per iteration, so the ratio prices
    the bucketed layout against the degree-regular workload XLA loves.
    Acceptance (asserted in-suite at test shapes): the bucketed power-law
    rate stays within 4× of the padded equal-edge RRG rate. Null + reason
    on any failure, never 0.0."""
    import jax
    import jax.numpy as jnp

    from benchmarks.common import draw_u32
    from graphdyn import obs
    from graphdyn.graphs import (
        degree_buckets,
        degree_cv,
        powerlaw_graph,
        random_regular_graph,
    )
    from graphdyn.ops.bucketed import bucketed_rollout
    from graphdyn.ops.packed import packed_rollout

    defaults = (8192, 256, 10, 2) if smoke else (100_000, 1024, 20, 3)
    n = n if n is not None else defaults[0]
    R = R if R is not None else defaults[1]
    steps = steps if steps is not None else defaults[2]
    iters = iters if iters is not None else defaults[3]
    W = R // 32

    g = powerlaw_graph(n, gamma=2.2, dmin=2, seed=0)
    b = degree_buckets(g)
    st = jnp.asarray(draw_u32(0, (n, W)))
    st = bucketed_rollout(b, st, steps)           # compile + warm
    _sync(st)
    with obs.timed("bench.powerlaw_rate", layout="bucketed") as sw:
        for _ in range(iters):
            st = bucketed_rollout(b, st, steps)
        _sync(st)
    bucketed = n * R * steps * iters / sw.wall_s
    obs.gauge("ops.bucketed.rate", bucketed, n=n, R=R)
    _mark(f"powerlaw bucketed: n={n} dmax={int(g.dmax)} "
          f"rate {bucketed:.3e}")

    # equal-edge padded control: d = round(2E/n), bumped to keep n·d even
    d = max(3, int(round(float(g.deg.sum()) / n)))
    if (n * d) % 2:
        d += 1
    gr = random_regular_graph(n, d, seed=0)
    nbr = jnp.asarray(gr.nbr)
    deg = jnp.asarray(gr.deg)
    f = jax.jit(lambda x: packed_rollout(nbr, deg, x, steps),
                donate_argnums=0)
    st = f(jnp.asarray(draw_u32(1, (n, W))))
    _sync(st)
    with obs.timed("bench.powerlaw_rate", layout="padded_rrg") as sw:
        for _ in range(iters):
            st = f(st)
        _sync(st)
    padded = n * R * steps * iters / sw.wall_s
    _mark(f"powerlaw control RRG d={d}: rate {padded:.3e} "
          f"(rrg/bucketed {padded / bucketed:.2f}x)")
    return {
        "powerlaw_rate": bucketed,
        "powerlaw_rate_detail": {
            "rrg_padded_rate": padded,
            "rrg_over_bucketed_x": padded / bucketed,
            "hub_degree": int(g.deg.max()),
            "degree_cv": degree_cv(g.deg),
            "table_entries": int(b.table_entries),
            "padded_entries": int(n) * int(g.dmax),
            "workload": {"n": n, "gamma": 2.2, "dmin": 2, "d_rrg": d,
                         "R": R, "steps": steps, "iters": iters},
        },
    }


def stream_rate_row(smoke: bool, *, n=None, R=None, steps=None,
                    iters=None):
    """Out-of-core streamed rollout (``graphdyn.ops.streamed``) on an
    adjacency whose RESIDENT working set exceeds a clamped device budget:
    the budget is pinned at 1/4 of the modeled resident bucketed bytes,
    so the plan MUST chunk (several chunks, host-resident) and the row
    prices exactly the regime the engine exists for. Two legs over the
    same plan: ``prefetch_depth=0`` (forced-synchronous gathers — the
    overlap baseline) vs ``prefetch_depth=2`` (double-buffered host
    prefetch), so ``hiding_frac`` reports how much of the gather wall
    clock the overlap actually hides (the acceptance gate — >= 50% — is
    asserted by the slow-tier test at its own shapes; the bench row only
    reports). Null + reason on any failure, never 0.0."""
    from benchmarks.common import draw_u32
    from graphdyn import obs
    from graphdyn.graphs import degree_buckets, powerlaw_graph
    from graphdyn.obs import memband
    from graphdyn.ops.streamed import build_stream_plan, streamed_rollout

    defaults = (8192, 256, 6, 2) if smoke else (65536, 1024, 10, 2)
    n = n if n is not None else defaults[0]
    R = R if R is not None else defaults[1]
    steps = steps if steps is not None else defaults[2]
    iters = iters if iters is not None else defaults[3]
    W = R // 32

    g = powerlaw_graph(n, gamma=2.2, dmin=2, seed=0)
    resident = int(memband.bucketed_state_bytes(
        n, W, int(degree_buckets(g).table_entries)))
    # 1/4 of the resident model forces several chunks; the worst hub's
    # single-node feasibility floor (×2: double-buffered) is the hard
    # lower clamp — below it no chunking exists at all
    budget = max(resident // 4,
                 2 * int(memband.streamed_min_bytes(int(g.deg.max()), W)))
    plan = build_stream_plan(g, W=W, device_budget_bytes=budget)
    legs: dict = {}
    for depth in (0, 2):
        sp = np.asarray(draw_u32(0, (n, W)))
        stats: dict = {}
        streamed_rollout(g, sp, 1, plan=plan, prefetch_depth=depth)  # warm
        with obs.timed("bench.stream_rate", depth=depth) as sw:
            for _ in range(iters):
                streamed_rollout(g, sp, steps, plan=plan,
                                 prefetch_depth=depth, stats_out=stats)
        legs[depth] = {"wall_s": sw.wall_s, "stats": stats}
    wall0 = legs[0]["wall_s"]
    wall2 = legs[2]["wall_s"]
    rate = n * R * steps * iters / wall2
    obs.gauge("ops.streamed.rate", rate, n=n, R=R,
              chunks=len(plan.chunks))
    _mark(f"stream rate: n={n} chunks={len(plan.chunks)} "
          f"budget {budget} rate {rate:.3e} "
          f"(sync/overlap {wall0 / wall2:.2f}x)")
    return {
        "stream_rate": rate,
        "stream_rate_detail": {
            "sync_rate": n * R * steps * iters / wall0,
            "hiding_frac": max(0.0, 1.0 - wall2 / wall0),
            "overlap_frac": float(
                legs[2]["stats"].get("overlap_frac", 0.0)),
            "chunks": len(plan.chunks),
            "device_budget_bytes": budget,
            "resident_model_bytes": resident,
            "h2d_bytes": int(legs[2]["stats"].get("h2d_bytes", 0)),
            "workload": {"n": n, "gamma": 2.2, "dmin": 2, "R": R,
                         "steps": steps, "iters": iters},
        },
    }


def churn_rate_row(smoke: bool, *, n=None, R=None, steps=None,
                   churn_per_step=None):
    """Live edge churn through the streamed engine: a seeded mutation
    schedule (``graphdyn.ops.streamed.seeded_churn``) applied at chunk
    boundaries with incremental rebuild of exactly the touched chunks,
    while the rollout keeps advancing. The row is applied mutations per
    second (schedule candidates surviving the idempotent filters, over
    the mutation+rebuild wall clock — plan build time excluded); the
    spin-update rate rides in the detail as proof the dynamics never
    stalled. Null + reason on any failure, never 0.0."""
    from benchmarks.common import draw_u32
    from graphdyn import obs
    from graphdyn.graphs import powerlaw_graph
    from graphdyn.ops.streamed import seeded_churn, streamed_rollout

    defaults = (4096, 256, 8, 64.0) if smoke else (32768, 512, 12, 512.0)
    n = n if n is not None else defaults[0]
    R = R if R is not None else defaults[1]
    steps = steps if steps is not None else defaults[2]
    churn_per_step = (churn_per_step if churn_per_step is not None
                      else defaults[3])
    W = R // 32

    g = powerlaw_graph(n, gamma=2.2, dmin=2, seed=0)
    schedule = seeded_churn(n, steps, rate=churn_per_step, seed=7)
    sp = np.asarray(draw_u32(0, (n, W)))
    stats: dict = {}
    with obs.timed("bench.churn_rate", n=n) as sw:
        streamed_rollout(g, sp, steps, n_chunks=4, churn=schedule,
                         stats_out=stats)
    applied = int(stats.get("mutations", 0))
    wall = max(sw.wall_s - float(stats.get("build_s", 0.0)), 1e-9)
    rate = applied / wall
    obs.gauge("ops.streamed.churn_rate", rate, n=n, applied=applied)
    _mark(f"churn rate: n={n} applied={applied} rate {rate:.3e}/s")
    return {
        "churn_rate": rate,
        "churn_rate_detail": {
            "applied_mutations": applied,
            "scheduled_batches": len(schedule),
            "spin_update_rate": n * R * steps / sw.wall_s,
            "workload": {"n": n, "R": R, "steps": steps,
                         "churn_per_step": churn_per_step, "seed": 7},
        },
    }


def stream_shard_scaling_row(smoke: bool, *, n_per=None, R=None,
                             steps=None, iters=None):
    """Weak scaling of the sharded streamed engine
    (``graphdyn.parallel.stream``): FIXED bytes per shard — each of P
    shards owns ``n_per`` power-law nodes and streams them under the SAME
    per-shard device budget (1/4 of the P=1 resident model, so every
    shard MUST chunk), P ∈ {1, 2, 4, 8} capped at the device pool;
    efficiency = rate(P) / (P · rate(1)). The P=1 leg is the unsharded
    ``streamed_rollout`` on the identical budget — exactly the
    ``partition=`` path's identity — so the column prices the ppermute
    exchange + shard bookkeeping and nothing else. Fewer than 2 devices
    emits null + reason, never 0.0."""
    import jax

    from benchmarks.common import draw_u32
    from graphdyn import obs
    from graphdyn.graphs import (
        degree_buckets,
        partition_graph,
        powerlaw_graph,
    )
    from graphdyn.obs import memband
    from graphdyn.ops.streamed import build_stream_plan, streamed_rollout
    from graphdyn.parallel.mesh import make_mesh
    from graphdyn.parallel.stream import sharded_streamed_rollout

    # ONE device pool for every leg (same discipline as halo_weak_scaling):
    # mixing platforms across P would bench hardware, not the exchange
    pool = jax.devices()
    if len(pool) < 2:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= 2:
            pool = cpu
    if len(pool) < 2:
        reason = (
            f"sharded stream scaling needs >= 2 devices on one platform "
            f"(have {len(pool)}); on CPU force a simulated host mesh: "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
        return {
            "stream_shard_efficiency": None,
            "stream_shard_efficiency_skipped_reason": reason,
        }
    avail = len(pool)

    defaults = (512, 128, 5, 2) if smoke else (8192, 512, 8, 2)
    n_per = n_per if n_per is not None else defaults[0]
    R = R if R is not None else defaults[1]
    steps = steps if steps is not None else defaults[2]
    iters = iters if iters is not None else defaults[3]
    W = R // 32

    # the per-shard budget is FIXED from the P=1 graph's resident model:
    # every P leg hands each shard the same bytes, so each shard's chunk
    # run stays ~constant and the efficiency column isolates the exchange
    g1 = powerlaw_graph(n_per, gamma=2.2, dmin=2, seed=0)
    resident = int(memband.bucketed_state_bytes(
        n_per, W, int(degree_buckets(g1).table_entries)))
    base_budget = resident // 4

    rates: dict[str, float] = {}
    chunks_by_p: dict[str, int] = {}
    for Pn in (1, 2, 4, 8):
        if Pn > avail:
            break
        g = powerlaw_graph(Pn * n_per, gamma=2.2, dmin=2, seed=0)
        # the single-node feasibility floor is per-graph: the widest row
        # must fit one device, double-buffered (same clamp as stream_rate)
        budget = max(base_budget,
                     2 * int(memband.streamed_min_bytes(
                         int(g.deg.max()), W)))
        sp = np.asarray(draw_u32(0, (g.n, W)))
        stats: dict = {}
        if Pn == 1:
            plan = build_stream_plan(g, W=W, device_budget_bytes=budget)
            streamed_rollout(g, sp, 1, plan=plan)  # warm
            with obs.timed("bench.stream_shard", P=Pn) as sw:
                for _ in range(iters):
                    streamed_rollout(g, sp, steps, plan=plan,
                                     stats_out=stats)
        else:
            part = partition_graph(g, Pn, seed=0)
            mesh = make_mesh((Pn,), ("node",), devices=pool[:Pn])
            sharded_streamed_rollout(g, sp, 1, n_shards=Pn,
                                     device_budget_bytes=budget,
                                     partition=part, mesh=mesh)  # warm
            with obs.timed("bench.stream_shard", P=Pn) as sw:
                for _ in range(iters):
                    sharded_streamed_rollout(
                        g, sp, steps, n_shards=Pn,
                        device_budget_bytes=budget, partition=part,
                        mesh=mesh, stats_out=stats)
        rates[str(Pn)] = g.n * R * steps * iters / sw.wall_s
        chunks_by_p[str(Pn)] = int(stats.get("chunks", 0))
        obs.gauge("ops.stream_shard.rate", rates[str(Pn)], P=Pn, n=g.n,
                  R=R)
        _mark(f"stream shard scaling P={Pn}: n={g.n} "
              f"rate {rates[str(Pn)]:.3e}")
    p_max = max(int(k) for k in rates)
    return {
        "stream_shard_efficiency": rates[str(p_max)] / (p_max * rates["1"]),
        "stream_shard_rate_by_shards": rates,
        "stream_shard_workload": {
            "n_per_shard": n_per, "gamma": 2.2, "dmin": 2, "R": R,
            "steps": steps, "iters": iters, "P_max": p_max,
            "budget_per_shard_bytes": base_budget,
            "chunks_by_shards": chunks_by_p,
            "platform": pool[0].platform,
        },
    }


def churn_repartition_rate_row(smoke: bool, *, n=None, R=None, steps=None,
                               churn_per_step=None):
    """Live churn-driven repartition through the SHARDED streamed engine
    (``graphdyn.parallel.stream``): a seeded high-rate mutation schedule
    pushes nodes across the hub threshold while P=2 shards keep
    advancing — promotions become vertex-cut hubs (and fallen hubs
    demote) at chunk boundaries, with only the touched chunks and the
    exchange schedule rebuilt. The row is applied mutations per second
    over the mutation + rebuild wall clock (plan build excluded) with
    repartition live; the detail carries the repartition and
    rebuilt-chunk counts as proof the re-layout actually fired. Fewer
    than 2 devices emits null + reason, never 0.0."""
    import jax

    from benchmarks.common import draw_u32
    from graphdyn import obs
    from graphdyn.graphs import powerlaw_graph
    from graphdyn.ops.streamed import seeded_churn
    from graphdyn.parallel.mesh import make_mesh
    from graphdyn.parallel.stream import sharded_streamed_rollout

    pool = jax.devices()
    if len(pool) < 2:
        try:
            cpu = jax.devices("cpu")
        except RuntimeError:
            cpu = []
        if len(cpu) >= 2:
            pool = cpu
    if len(pool) < 2:
        reason = (
            f"sharded churn repartition needs >= 2 devices on one "
            f"platform (have {len(pool)}); on CPU force a simulated host "
            "mesh: XLA_FLAGS=--xla_force_host_platform_device_count=8"
        )
        return {
            "churn_repartition_rate": None,
            "churn_repartition_rate_skipped_reason": reason,
        }

    defaults = (1024, 128, 6, 32.0) if smoke else (16384, 512, 12, 512.0)
    n = n if n is not None else defaults[0]
    R = R if R is not None else defaults[1]
    steps = steps if steps is not None else defaults[2]
    churn_per_step = (churn_per_step if churn_per_step is not None
                      else defaults[3])
    W = R // 32

    g = powerlaw_graph(n, gamma=2.2, dmin=2, seed=0)
    # a threshold straddled by the degree tail: churn at this rate pushes
    # nodes across it in both directions, so the drive exercises promote
    # AND demote repartitions (counts land in the detail)
    thr = max(int(g.deg.max()) // 2, 4)
    schedule = seeded_churn(n, steps, rate=churn_per_step, seed=7)
    mesh = make_mesh((2,), ("node",), devices=pool[:2])
    sp = np.asarray(draw_u32(0, (n, W)))
    stats: dict = {}
    with obs.timed("bench.churn_repartition", n=n) as sw:
        sharded_streamed_rollout(g, sp, steps, n_shards=2, n_chunks=4,
                                 hub_threshold=thr, mesh=mesh,
                                 churn=schedule, stats_out=stats)
    applied = int(stats.get("mutations", 0))
    wall = max(sw.wall_s - float(stats.get("build_s", 0.0)), 1e-9)
    rate = applied / wall
    obs.gauge("ops.stream_shard.churn_rate", rate, n=n, applied=applied,
              repartitions=int(stats.get("repartitions", 0)))
    _mark(f"churn repartition rate: n={n} applied={applied} "
          f"repartitions={stats.get('repartitions', 0)} "
          f"rate {rate:.3e}/s")
    return {
        "churn_repartition_rate": rate,
        "churn_repartition_rate_detail": {
            "applied_mutations": applied,
            "repartitions": int(stats.get("repartitions", 0)),
            "chunks_rebuilt": int(stats.get("chunks_rebuilt", 0)),
            "scheduled_batches": len(schedule),
            "spin_update_rate": n * R * steps / sw.wall_s,
            "hub_threshold": thr,
            "shards": 2,
            "workload": {"n": n, "R": R, "steps": steps,
                         "churn_per_step": churn_per_step, "seed": 7},
        },
    }


def tta_rows(smoke: bool):
    """Time-to-target-magnetization A/B (ROADMAP item 3): device steps
    until the rolled-out end-state magnetization first reaches the target,
    for the serial reference SA chain vs the replica-exchange ladder
    (``graphdyn.search.tempering``) and the chromatic block sweeps
    (``graphdyn.search.chromatic``), on the SAME d=3 RRG at fixed seeds —
    legs interleaved per seed. Device steps is the honest unit: the serial
    chain pays one device step per proposal (one light cone), the ladder
    pays one per lockstep lane step, the chromatic kernel one per color
    class (~n/χ proposals). Counts are seed-deterministic, so the rows
    reproduce exactly — this is an algorithmic A/B, not a timing one (the
    obs spans still record the wall clock per leg).

    ``swap_acceptance_rate`` rides as its own column: a DEAD ladder (0%
    swaps accepted) would still look "fast" on easy seeds, so benchcheck
    fails the round loudly when the measured row carries a zero rate. A
    serial chain that exhausts its step budget before the target counts at
    the budget (speedups become lower bounds; ``tta_serial_timeouts``
    records how often)."""
    from graphdyn import obs
    from graphdyn.config import DynamicsConfig, SAConfig
    from graphdyn.graphs import random_regular_graph
    from graphdyn.search.chromatic import chromatic_anneal
    from graphdyn.search.fused import fused_anneal
    from graphdyn.search.tempering import temper_search

    if smoke:
        n, seeds, max_steps, lanes, max_sweeps = 128, (0, 1), 400_000, 8, 4000
    else:
        n, seeds, max_steps, lanes, max_sweeps = (
            512, (0, 1, 2), 2_000_000, 16, 20_000)
    m_target = 0.9
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    g = random_regular_graph(n, 3, seed=0)
    serial, temper, chrom, swap_rates = [], [], [], []
    serial_timeouts = 0
    chi = None
    chrom_hits = chrom_total = 0
    fused, fused_hits, fused_total = [], 0, 0
    fused_chi = None
    fused_kernel = None
    for seed in seeds:                    # interleaved A/B per seed
        _mark(f"tta seed={seed}: serial reference chain")
        with obs.timed("bench.tta", leg="serial", seed=seed):
            ser = temper_search(
                g, cfg, betas=[1.0], seed=seed, max_steps=max_steps,
                swap_moves=False, swap_interval=10_000,
                m_target=m_target, stop_on_first=True,
            )
        if ser.steps_to_target < 0:       # budget exhausted: lower bound
            serial_timeouts += 1
            serial.append(max_steps)
        else:
            serial.append(ser.steps_to_target)
        _mark(f"tta seed={seed}: tempering ladder (K={lanes})")
        with obs.timed("bench.tta", leg="tempering", seed=seed):
            lad = temper_search(
                g, cfg, n_lanes=lanes, seed=seed, max_steps=max_steps,
                swap_interval=250, m_target=m_target, stop_on_first=True,
            )
        temper.append(lad.steps_to_target)
        swap_rates.append(lad.swap_acceptance_rate)
        _mark(f"tta seed={seed}: chromatic sweeps")
        with obs.timed("bench.tta", leg="chromatic", seed=seed):
            ch = chromatic_anneal(
                g, cfg, n_replicas=32, seed=seed, m_target=m_target,
                max_sweeps=max_sweeps,
            )
        chi = ch.chi
        hit = ch.steps_to_target >= 0
        chrom_hits += int(hit.sum())
        chrom_total += hit.size
        # mean first-passage per chain (each packed replica is an
        # independent chain; min would overclaim the parallel-draw bonus)
        chrom.append(float(np.mean(ch.steps_to_target[hit])) if hit.any()
                     else np.nan)
        _mark(f"tta seed={seed}: fused one-kernel annealer")
        with obs.timed("bench.tta", leg="fused", seed=seed):
            fr = fused_anneal(
                g, cfg, n_replicas=32, seed=seed, m_target=m_target,
                max_sweeps=max_sweeps,
            )
        fused_chi = fr.chi
        fused_kernel = fr.kernel_used
        fhit = fr.steps_to_target >= 0
        fused_hits += int(fhit.sum())
        fused_total += fhit.size
        fused.append(float(np.mean(fr.steps_to_target[fhit]))
                     if fhit.any() else np.nan)
    if any(t < 0 for t in temper):
        return {
            "tta_tempering": None,
            "tta_tempering_skipped_reason":
                "tempering ladder exhausted its step budget before the "
                "target on at least one seed — no honest speedup to report",
            "tta_chromatic": None,
            "tta_chromatic_skipped_reason": "tempering leg failed",
            "tta_fused": None,
            "tta_fused_skipped_reason": "tempering leg failed",
            "swap_acceptance_rate": None,
        }
    chrom_row: dict
    if chrom_hits < chrom_total:
        # a replica that never reached the target has TTA > the sweep
        # budget: averaging only the hits (or substituting the budget)
        # would UNDERSTATE the chromatic time and bench a miss as fast —
        # null + reason instead, exactly like the tempering leg
        chrom_row = {
            "tta_chromatic": None,
            "tta_chromatic_skipped_reason": (
                f"only {chrom_hits}/{chrom_total} chromatic chains reached "
                f"m_target={m_target} within {max_sweeps} sweeps — no "
                "honest speedup to report"
            ),
        }
    else:
        chrom_row = {"tta_chromatic": {
            "device_steps": float(np.mean(chrom)),
            "speedup_x": float(np.sum(serial) / max(np.sum(chrom), 1e-9)),
            "per_seed_speedup": [s / max(c, 1e-9)
                                 for s, c in zip(serial, chrom)],
            "chi": chi,
            "target_hit_fraction": 1.0,
        }}
    fused_row: dict
    if fused_hits < fused_total:
        # same honesty rule as the chromatic leg: a replica that never
        # reached the target has TTA > the sweep budget — null + reason,
        # never a flattering average over the hits
        fused_row = {
            "tta_fused": None,
            "tta_fused_skipped_reason": (
                f"only {fused_hits}/{fused_total} fused chains reached "
                f"m_target={m_target} within {max_sweeps} sweeps — no "
                "honest speedup to report"
            ),
        }
    else:
        fused_row = {"tta_fused": {
            "device_steps": float(np.mean(fused)),
            "speedup_x": float(np.sum(serial) / max(np.sum(fused), 1e-9)),
            "per_seed_speedup": [s / max(f, 1e-9)
                                 for s, f in zip(serial, fused)],
            "chi": fused_chi,
            "kernel": fused_kernel,
            "target_hit_fraction": 1.0,
        }}
    # the rider A/B: what the per-chunk bool(jnp.any) stop test costs a
    # fixed-budget ladder (sync_stop True vs False — results bit-identical,
    # tested; this measures only the drive-loop sync). Interleaved after a
    # shared warm-up so both legs run the same compiled program.
    ab_kw = dict(n_lanes=4, seed=0, max_steps=4000, swap_interval=250,
                 m_target=m_target)
    temper_search(g, cfg, sync_stop=True, **ab_kw)      # compile + warm
    ab = {}
    for label, sync in (("sync", True), ("nosync", False)):
        with obs.timed("bench.tta_sync_ab", leg=label) as sw:
            temper_search(g, cfg, sync_stop=sync, **ab_kw)
        ab[label] = sw.wall_s
    row = {
        "tta_workload": {
            "n": n, "d": 3, "seeds": list(seeds), "m_target": m_target,
            "max_steps": max_steps, "lanes": lanes,
            "chromatic_replicas": 32, "fused_replicas": 32,
        },
        "tta_serial_steps": float(np.mean(serial)),
        "tta_serial_timeouts": serial_timeouts,
        "tta_tempering": {
            "device_steps": float(np.mean(temper)),
            "speedup_x": float(np.sum(serial) / max(np.sum(temper), 1)),
            "per_seed_speedup": [s / max(t, 1)
                                 for s, t in zip(serial, temper)],
            "lanes": lanes,
        },
        "swap_acceptance_rate": float(np.mean(swap_rates)),
        "tta_fixed_budget_sync": {
            "sync_s": ab["sync"], "nosync_s": ab["nosync"],
            "sync_saved_x": ab["sync"] / max(ab["nosync"], 1e-9),
        },
        **chrom_row,
        **fused_row,
    }
    obs.gauge("search.tta.speedup", row["tta_tempering"]["speedup_x"],
              leg="tempering")
    if row["tta_chromatic"] is not None:
        obs.gauge("search.tta.speedup", row["tta_chromatic"]["speedup_x"],
                  leg="chromatic")
    if row["tta_fused"] is not None:
        obs.gauge("search.tta.speedup", row["tta_fused"]["speedup_x"],
                  leg="fused")
    obs.gauge("search.swap_acceptance_rate", row["swap_acceptance_rate"])
    return row


def fused_sa_rate_row(smoke: bool):
    """Proposal throughput of the fused one-kernel annealer
    (``graphdyn.ops.pallas_anneal`` via ``search.fused_anneal``):
    spin-update proposals/s — every site of every replica is proposed once
    per sweep, so the count is ``n·R·sweeps`` over the measured wall. The
    RATE is chip-only (null + reason on CPU: interpret mode measures the
    interpreter, and the XLA twin on a 2-core host measures the host);
    the CPU container instead proves interpret-vs-XLA bit parity in
    tier-1. Device-step counts stay seed-deterministic, so a chip round's
    ``tta_fused`` row must match the CPU rows bit-for-bit (checklist
    item 6 in scripts/pallas_tpu_validate.py)."""
    import jax

    backend = jax.default_backend()
    if backend not in ("tpu", "axon"):
        return {
            "fused_sa_rate": None,
            "fused_sa_rate_skipped_reason": (
                "fused-annealer rate is chip-only (backend=%s); the CPU "
                "container proves interpret-mode parity, not throughput"
                % backend
            ),
        }
    from graphdyn import obs
    from graphdyn.config import DynamicsConfig, SAConfig
    from graphdyn.graphs import random_regular_graph
    from graphdyn.ops.pallas_anneal import build_fused_tables
    from graphdyn.search.fused import fused_anneal

    n, R, sweeps = (4096, 64, 64) if smoke else (16384, 256, 256)
    cfg = SAConfig(dynamics=DynamicsConfig(p=1, c=1))
    g = random_regular_graph(n, 3, seed=0)
    tables = build_fused_tables(g, cfg, seed=0)   # amortized, host-side
    kw = dict(n_replicas=R, seed=0, m_target=1.0, tables=tables,
              chunk_sweeps=sweeps)
    _mark(f"fused_sa_rate n={n} R={R}: warmup (compile)")
    fused_anneal(g, cfg, max_sweeps=sweeps, **kw)
    _mark("fused_sa_rate: timing")
    with obs.timed("bench.fused_sa_rate", n=n, R=R) as sw:
        res = fused_anneal(g, cfg, max_sweeps=sweeps, **kw)
    rate = float(n) * R * res.sweeps / sw.wall_s
    obs.gauge("search.fused.rate", rate, n=n, R=R)
    return {
        "fused_sa_rate": rate,
        "fused_sa_workload": {"n": n, "d": 3, "R": R,
                              "sweeps": res.sweeps, "chi": res.chi,
                              "kernel": res.kernel_used},
    }


def fingerprint_rows():
    """The graftcheck program-fingerprint summary persisted with every
    round (``BENCH_*.json``): per headline entry point, the ledger-gated
    structural fields (op-category counts, fusion count, while-loop count,
    donated-parameter set, largest baked constant). benchcheck diffs these
    against the previous round's row, so a structural regression in a
    headline program shows up round-over-round even when the TPU was
    unreachable and no rate row carries signal (ROADMAP item 5 — three of
    five rounds measured nothing). Fingerprints are backend-specific, so
    the backend rides in the row and the diff only compares same-backend
    rounds."""
    import jax

    from graphdyn.analysis.graftcheck import collect_fingerprints

    return {
        "backend": jax.default_backend(),
        "entries": collect_fingerprints(compact=True, diag=_mark),
    }


def torch_cpu_rate(g, steps=3):
    import torch

    from graphdyn import obs

    nbr_t = torch.as_tensor(np.asarray(g.nbr).astype(np.int64))
    rng = np.random.default_rng(0)
    s = torch.as_tensor((2 * rng.integers(0, 2, size=g.n) - 1).astype(np.int64))
    sums = torch.sum(s[nbr_t], dim=1)
    _ = (1 - torch.abs(torch.sign(sums))) * s + torch.sign(sums)
    with obs.timed("bench.torch_cpu_rate", n=g.n) as sw:
        for _ in range(steps):
            sums = torch.sum(s[nbr_t], dim=1)
            s = (1 - torch.abs(torch.sign(sums))) * s + torch.sign(sums)
    return g.n * steps / sw.wall_s


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="small shapes, fast")
    args = ap.parse_args()

    import os

    # Probe-before-init: a single long wait on a wedged relay loses the
    # capture (BENCH_r01/r03/r04 all recorded 0.0 that way) while the relay
    # demonstrably recovers in minutes-long windows. Probe in subprocesses
    # until the budget is spent; if the relay never answers, fall back to
    # CPU so a real, honestly-labeled number lands instead of an error row.
    # An explicit GRAPHDYN_FORCE_PLATFORM skips the probe: 'cpu' cannot
    # hang, and 'axon' means the caller (the chip-session watcher, which
    # fires only on a canary UP) wants chip-or-hang semantics.
    from benchmarks.common import guarded_capture_init

    # probe-or-fallback + init watchdog + first device touch, shared with
    # the physics capture scripts (one chip-or-hang preamble everywhere)
    relay_note = guarded_capture_init(fail_row={
        "metric": "spin_updates_per_sec_per_chip_d3_rrg",
        "value": 0.0,
        "unit": "spin-updates/s",
        "vs_baseline": 0.0,
    })
    import jax

    from graphdyn.graphs import random_regular_graph

    # every round records its own obs event ledger (spans + rate gauges +
    # compile counters); the row carries the path and the manifest hash so
    # the round artifact names its telemetry. Failure to set one up is a
    # null + reason in the row — never silent.
    import atexit
    import contextlib
    import hashlib

    from graphdyn import obs

    obs_row = {}
    _obs_stack = contextlib.ExitStack()
    atexit.register(_obs_stack.close)
    try:
        import tempfile

        obs_ledger = os.environ.get("GRAPHDYN_OBS") or os.path.join(
            tempfile.gettempdir(), f"graphdyn_obs_bench_{os.getpid()}.jsonl"
        )
        _obs_stack.enter_context(obs.recording(obs_ledger))
        # GRAPHDYN_PROFILE=DIR: capture an aligned jax.profiler trace of
        # the whole bench run — every obs span doubles as a TraceAnnotation
        # carrying its ledger name-path (no-op when the env var is unset)
        _obs_stack.enter_context(obs.trace.profiling())
        run = obs.manifest(**obs.run_manifest_fields(
            cmd="bench", smoke=bool(args.smoke),
        ))
        obs_row = {
            "obs_ledger": obs_ledger,
            "obs_manifest_sha": hashlib.sha1(
                json.dumps(run, sort_keys=True, default=str).encode()
            ).hexdigest()[:16],
        }
    except Exception as e:  # noqa: BLE001 — telemetry must not kill bench
        _mark(f"obs recorder setup failed: {str(e)[:150]}")
        obs_row = {
            "obs_ledger": None,
            "obs_ledger_skipped_reason":
                f"obs recorder setup failed: {str(e)[:150]}",
        }

    if args.smoke:
        n, R_packed, R_int8, steps = 100_000, 1024, 8, 5
    else:
        n, R_packed, R_int8, steps = 1_000_000, 4096, 64, 20
        if jax.default_backend() == "cpu":
            # a CPU fallback (wedged TPU relay) at full step counts runs
            # for hours and the driver records a timeout instead of a
            # number; full-scale ARRAYS with minimal steps still measure a
            # valid per-second rate (the emitted "steps" field records the
            # degradation)
            steps = 2

    from graphdyn.graphs import bfs_order, permute_nodes

    # partial rates survive a mid-run device failure (tunnel wedge): on any
    # exception past this point the best rate measured so far is emitted as
    # an error JSON instead of dying with a bare traceback and empty stdout
    partial = {"packed_rate_natural_order": 0.0, "packed_rate_bfs_order": 0.0,
               "packed_rate_wide": 0.0, "packed_rate_pallas": 0.0,
               "int8_rate": 0.0}
    # rows that were SKIPPED (backend unsupported / optional row failed)
    # emit null + a reason, never 0.0: a skip must be unmistakable from a
    # measured collapse, or the benchmark trajectory reads it as a
    # regression (BENCH_r05 recorded packed_rate_wide/pallas 0.0 that way)
    skipped = {}
    # driver-throughput rates ride along in both emissions but stay outside
    # `partial` (whose values feed the headline max() over kernel rates)
    extra = {}
    # per-rung widening rates: measured in scarce chip time, so they ride
    # along in the failure emission too
    wide_by_R = {}

    def _rows():
        out = dict(partial)
        for key, reason in skipped.items():
            if not out.get(key):
                out[key] = None
                out[key + "_skipped_reason"] = reason
        return out

    def _fail(e, stage="device"):
        best = max(v for v in partial.values())
        row = {
            "metric": "spin_updates_per_sec_per_chip_d3_rrg_n%d" % n,
            "value": best,
            "unit": "spin-updates/s",
            "vs_baseline": 0.0,
            "error": f"{stage} failed mid-run: {str(e)[:200]}",
            **_rows(),
            **extra,
            "packed_rate_wide_by_R": wide_by_R,
            **obs_row,
            **peak_hbm_row(),
            "backend": jax.default_backend(),
            **({"relay": relay_note} if relay_note else {}),
        }
        row.update(trend_gate(row))
        _obs_stack.close()      # uninstall the recorder (in-process callers)
        print(json.dumps(row))
        return 0 if best > 0 else 2

    _mark(f"building d=3 RRG n={n}")
    g = random_regular_graph(n, 3, seed=0)
    try:
        rate_natural = packed_rate(g, R_packed, steps)
        partial["packed_rate_natural_order"] = rate_natural
        _mark(f"natural order rate {rate_natural:.3e}; BFS reorder")
        # BFS node relabeling: neighbors' spin-word rows land near each
        # other in HBM, improving gather locality (dynamics are
        # label-equivariant, tested)
        g_bfs, _ = permute_nodes(g, bfs_order(g))
        rate_bfs = packed_rate(g_bfs, R_packed, steps)
        partial["packed_rate_bfs_order"] = rate_bfs
    except Exception as e:  # noqa: BLE001 — emit partials, then bail
        return _fail(e)
    _mark(f"bfs order rate {rate_bfs:.3e}; wide-replica row")
    # wide-replica lever: updates/row-access scale with W while bytes/update
    # stay constant, so if the gather is access-rate-bound (not
    # bandwidth-bound) a 4x wider word is ~4x the headline. R=16384 is the
    # BASELINE config-5 chain count (1024 replicas x 16 temperatures); the
    # spin state is 2 GB at n=1e6 (plus the output double) — measured, and
    # skipped on OOM rather than guessed
    # The r04 chip window measured W 128->512 words as +47% at constant
    # bytes/update (effective HBM 132->194 GB/s): per-row issue cost still
    # amortizing with row size. So keep widening until OOM or the rate
    # rolls over: R = 4x and 8x the base (2 GB and 4 GB spin state at
    # n=1e6; each rung skipped on OOM rather than guessed).
    rate_wide, R_wide = 0.0, 0   # R_wide tracks only *measured* rungs
    from benchmarks.common import is_oom

    # the tunneled plugin reports "tpu"; hedge "axon" like every other
    # chip-backend allowlist in the repo (chip_doc_ok, CHIP_BACKENDS)
    on_chip = jax.default_backend() in ("tpu", "axon")
    # Widening is an HBM per-row-amortization lever; on the CPU fallback it
    # only burns minutes on host caches — chip-only. The 16x rung (W=2048,
    # 8 GB spin state) probes past the r04-measured W=512 point; OOM skips.
    if not on_chip:
        skipped["packed_rate_wide"] = (
            "wide-replica widening is chip-only (backend=%s)"
            % jax.default_backend()
        )
    for mult in (4, 8, 16) if on_chip else ():
        R_try = mult * R_packed
        try:
            r = packed_rate(g_bfs, R_try, max(steps // mult, 2))
        except Exception as e:  # noqa: BLE001 — OOM: skip the rung; else bail
            if not is_oom(e):
                return _fail(e)
            _mark(f"wide R={R_try} OOM; stopping the widening sweep")
            if not wide_by_R:
                skipped["packed_rate_wide"] = (
                    f"first widening rung R={R_try} OOMed"
                )
            break
        wide_by_R[str(R_try)] = r
        _mark(f"wide R={R_try} rate {r:.3e}")
        if r > rate_wide:
            rate_wide, R_wide = r, R_try
            # keep the failure emission's best-rate max() current: a later
            # rung dying must not discard this rung's measured rate
            partial["packed_rate_wide"] = rate_wide
        elif r < rate_wide:
            break  # rolled over — wider words no longer amortize
    partial["packed_rate_wide"] = rate_wide
    # per-row-DMA Pallas kernel A/B at the headline shape — the driver's
    # round-end bench run is a guaranteed chip window, so the A/B lands
    # even if the session watcher never fires. Chip-only (interpret mode is
    # not a rate); failure here must not cost the XLA rows
    rate_pallas = 0.0
    if on_chip:
        try:
            rate_pallas = packed_rate(g_bfs, R_packed, steps, kernel="pallas")
        except Exception as e:  # noqa: BLE001 — optional row
            _mark(f"pallas kernel row failed: {str(e)[:150]}")
            skipped["packed_rate_pallas"] = (
                f"pallas kernel row failed: {str(e)[:150]}"
            )
    else:
        skipped["packed_rate_pallas"] = (
            "pallas kernel row is chip-only (backend=%s)"
            % jax.default_backend()
        )
    partial["packed_rate_pallas"] = rate_pallas
    # headline + its replica count from ONE argmax over tracked (rate, R)
    # pairs — no float-equality reconstruction of which row won
    candidates = [(rate_natural, R_packed), (rate_bfs, R_packed),
                  (rate_wide, R_wide), (rate_pallas, R_packed)]
    value, packed_replicas_best = max(candidates, key=lambda rv: rv[0])
    _mark("ensemble driver A/B (grouped pipeline vs serial loop)")
    try:
        extra.update(ensemble_rate(args.smoke))
    except Exception as e:  # noqa: BLE001 — emit partials, then bail
        return _fail(e, stage="ensemble driver")
    _mark("entropy cell-ladder A/B (grouped cells vs serial cells)")
    try:
        extra.update(entropy_cell_rate(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never 0.0
        _mark(f"entropy cell rate row failed: {str(e)[:150]}")
        extra.update({
            "entropy_cell_rate": None,
            "entropy_cell_rate_skipped_reason":
                f"entropy cell A/B failed: {str(e)[:150]}",
            "entropy_cell_rate_pallas": None,
            "entropy_cell_rate_pallas_skipped_reason":
                f"entropy cell A/B failed: {str(e)[:150]}",
        })
    _mark("durable-store save overhead (ckpt_save_overhead)")
    try:
        extra.update(ckpt_save_overhead(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"ckpt save overhead row failed: {str(e)[:150]}")
        extra.update({
            "ckpt_save_overhead": None,
            "ckpt_save_overhead_skipped_reason":
                f"ckpt save A/B failed: {str(e)[:150]}",
        })
    _mark("liveness watchdog overhead (heartbeat_overhead)")
    try:
        extra.update(heartbeat_overhead(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"heartbeat overhead row failed: {str(e)[:150]}")
        extra.update({
            "heartbeat_overhead": None,
            "heartbeat_overhead_skipped_reason":
                f"heartbeat A/B failed: {str(e)[:150]}",
        })
    _mark("serve bucket hit rate (multi-tenant repeat-graph queue)")
    try:
        extra.update(serve_bucket_hit_rate(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"serve bucket hit rate row failed: {str(e)[:150]}")
        extra.update({
            "serve_bucket_hit_rate": None,
            "serve_bucket_hit_rate_skipped_reason":
                f"serve bucket drain failed: {str(e)[:150]}",
        })
    _mark("serve job latency (interleaved warm/cold p50/p99)")
    try:
        extra.update(serve_job_latency(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"serve job latency row failed: {str(e)[:150]}")
        extra.update({
            "serve_job_latency": None,
            "serve_job_latency_skipped_reason":
                f"serve latency A/B failed: {str(e)[:150]}",
        })
    _mark("halo weak scaling (node-axis sharding, fixed n/shard)")
    try:
        extra.update(halo_weak_scaling(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"halo weak scaling row failed: {str(e)[:150]}")
        extra.update({
            "halo_weak_efficiency": None,
            "halo_weak_efficiency_skipped_reason":
                f"halo weak scaling failed: {str(e)[:150]}",
            "halo_bytes_per_step": None,
            "halo_bytes_per_step_skipped_reason":
                f"halo weak scaling failed: {str(e)[:150]}",
        })
    _mark("powerlaw bucketed rate vs equal-edge RRG (powerlaw_rate)")
    try:
        extra.update(powerlaw_rate_row(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"powerlaw rate row failed: {str(e)[:150]}")
        extra.update({
            "powerlaw_rate": None,
            "powerlaw_rate_skipped_reason":
                f"powerlaw A/B failed: {str(e)[:150]}",
        })
    _mark("out-of-core streamed rollout rate (stream_rate)")
    try:
        extra.update(stream_rate_row(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"stream rate row failed: {str(e)[:150]}")
        extra.update({
            "stream_rate": None,
            "stream_rate_skipped_reason":
                f"streamed overlap A/B failed: {str(e)[:150]}",
        })
    _mark("live edge churn rate through the streamed engine (churn_rate)")
    try:
        extra.update(churn_rate_row(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"churn rate row failed: {str(e)[:150]}")
        extra.update({
            "churn_rate": None,
            "churn_rate_skipped_reason":
                f"churn drive failed: {str(e)[:150]}",
        })
    _mark("sharded streamed weak scaling (stream_shard_scaling)")
    try:
        extra.update(stream_shard_scaling_row(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"stream shard scaling row failed: {str(e)[:150]}")
        extra.update({
            "stream_shard_efficiency": None,
            "stream_shard_efficiency_skipped_reason":
                f"sharded stream scaling failed: {str(e)[:150]}",
        })
    _mark("churn-driven live repartition (churn_repartition_rate)")
    try:
        extra.update(churn_repartition_rate_row(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"churn repartition rate row failed: {str(e)[:150]}")
        extra.update({
            "churn_repartition_rate": None,
            "churn_repartition_rate_skipped_reason":
                f"sharded churn repartition drive failed: {str(e)[:150]}",
        })
    _mark("time-to-target search A/B (tta_tempering / tta_chromatic)")
    try:
        extra.update(tta_rows(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"tta rows failed: {str(e)[:150]}")
        extra.update({
            "tta_tempering": None,
            "tta_tempering_skipped_reason":
                f"tta A/B failed: {str(e)[:150]}",
            "tta_chromatic": None,
            "tta_chromatic_skipped_reason":
                f"tta A/B failed: {str(e)[:150]}",
            "tta_fused": None,
            "tta_fused_skipped_reason":
                f"tta A/B failed: {str(e)[:150]}",
            "swap_acceptance_rate": None,
        })
    _mark("fused one-kernel annealer rate (fused_sa_rate)")
    try:
        extra.update(fused_sa_rate_row(args.smoke))
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"fused sa rate row failed: {str(e)[:150]}")
        extra.update({
            "fused_sa_rate": None,
            "fused_sa_rate_skipped_reason":
                f"fused rate row failed: {str(e)[:150]}",
        })
    _mark("program fingerprints (graftcheck structural summary)")
    try:
        extra["fingerprints"] = fingerprint_rows()
    except Exception as e:  # noqa: BLE001 — optional row, never silent
        _mark(f"fingerprint row failed: {str(e)[:150]}")
        extra.update({
            "fingerprints": None,
            "fingerprints_skipped_reason":
                f"fingerprint collection failed: {str(e)[:150]}",
        })
    _mark("derived cost columns (graftcost ledger models)")
    try:
        from graphdyn.analysis.graftcost import bench_cost_columns

        # no compilation: the committed COST_LEDGER.json models evaluated
        # at this bench size (null + reason when the ledger cannot speak
        # for this backend)
        extra.update(bench_cost_columns(n))
    except Exception as e:  # noqa: BLE001 — optional columns, never silent
        _mark(f"derived cost columns failed: {str(e)[:150]}")
        reason = f"derived cost columns failed: {str(e)[:150]}"
        extra.update({
            "derived_bytes": None,
            "derived_bytes_skipped_reason": reason,
            "arithmetic_intensity": None,
            "arithmetic_intensity_skipped_reason": reason,
        })
    # progress log: a backend-skipped row says skipped(<reason>), NEVER a
    # zero rate — the JSON already emits null + <row>_skipped_reason, and
    # the human-readable line must be just as unmistakable
    def _rate_or_skip(row_key, rate):
        if row_key in skipped:
            return f"skipped({skipped[row_key]})"
        return f"{rate:.3e}"

    _mark(
        f"wide rate {_rate_or_skip('packed_rate_wide', rate_wide)}; "
        f"pallas rate {_rate_or_skip('packed_rate_pallas', rate_pallas)}; "
        f"int8 row"
    )
    try:
        v8 = int8_rate(g, R_int8, steps)
        partial["int8_rate"] = v8
    except Exception as e:  # noqa: BLE001 — emit partials, then bail
        return _fail(e)
    _mark(f"int8 rate {v8:.3e}; torch baseline")
    try:
        base = torch_cpu_rate(g)
    except Exception as e:  # noqa: BLE001 — emit the device rates we have
        return _fail(e, stage="torch-cpu baseline")
    row = {
        "metric": "spin_updates_per_sec_per_chip_d3_rrg_n%d" % n,
        "value": value,
        "unit": "spin-updates/s",
        # NOTE: the baseline divisor is the reference-style
        # SINGLE-THREADED torch-CPU kernel on this host
        "vs_baseline": value / base,
        "baseline_kind": "torch_cpu_single_thread",
        # skipped rows emit null + <row>_skipped_reason, never 0.0
        **_rows(),
        **extra,
        "packed_rate_wide_by_R": wide_by_R,
        # only when a rung actually ran — R_wide=0 otherwise (a
        # never-measured configuration must not report a count)
        **({"packed_replicas_wide": R_wide} if wide_by_R else {}),
        **obs_row,
        **peak_hbm_row(),
        "torch_cpu_rate": base,
        "packed_replicas": R_packed,
        "packed_replicas_best": packed_replicas_best,
        "steps": steps,
        # fraction of the kernel's own HBM-streaming bound on a
        # v5e-class chip (~800 GB/s => ~1.6e12 packed spin-updates/s
        # at n=1e6 d=3 — ARCHITECTURE.md roofline). The bound is
        # derived for the FULL shape, so report it only there (and
        # it is only meaningful when backend == tpu); smoke's n=1e5
        # working set is partly cache-resident, not HBM-streaming
        **(
            {"roofline_fraction_v5e": value / 1.6e12}
            if not args.smoke and on_chip else {}
        ),
        "backend": jax.default_backend(),
        **({"relay": relay_note} if relay_note else {}),
    }
    # the cross-round rate gate rides in the row (benchcheck asserts it
    # ran or was explicitly skipped, and fails on unblessed drift)
    row.update(trend_gate(row))
    # uninstall the recorder now rather than at interpreter exit — an
    # in-process caller (the contract tests) must not inherit a live
    # ledger; the atexit close stays as the crash-path backstop
    _obs_stack.close()
    print(json.dumps(row))
    return 0


if __name__ == "__main__":
    sys.exit(main())
